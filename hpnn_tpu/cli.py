"""train_nn / run_nn command-line drivers.

Flag-compatible rebuilds of the reference demo binaries
(``/root/reference/tests/train_nn.c``, ``tests/run_nn.c``):

    train_nn [-h] [-v]... [-x] [-O n] [-B n] [-S n]
             [--compile-cache DIR] [--corpus-cache DIR]
             [--epochs N] [--ckpt-every N] [--ckpt-dir DIR]
             [--ckpt-keep N] [--resume [PATH]]
             [--profile-dir DIR] [conf]
    run_nn   [-h] [-v]... [-O n] [-B n] [-S n]
             [--compile-cache DIR] [--corpus-cache DIR]
             [--ckpt-dir DIR] [--profile-dir DIR] [conf]

* flags combine (``-vvv``) and -O/-B/-S accept attached (``-O4``) or
  separated (``-O 4``) values, like the reference parser
  (``train_nn.c:100-199``); the ``--compile-cache``/``--corpus-cache``
  long options are rebuild extensions (persistent XLA program cache and
  packed-corpus cache location, mirroring ``serve_nn``);
* the conf file defaults to ``./nn.conf`` (``train_nn.c:215``);
* train_nn dumps the untrained kernel to ``kernel.tmp`` before training and
  the trained kernel to ``kernel.opt`` after (``train_nn.c:224-243``) --
  the checkpoint/resume workflow the tutorials build on.
"""

from __future__ import annotations

import os
import sys

from . import runtime
from .api import configure, run_kernel, train_kernel
from .utils import nn_log


def _help_text(name: str, train: bool) -> str:
    lines = [
        "***********************************",
        f"usage:  {name} [-options] [input]",
        "***********************************",
        "options:",
        "-h \tdisplay this help;",
        "-v \tincrease verbosity;",
    ]
    if train:
        lines.append("-x \tdiscard results.")
    lines += [
        "-O \tnumber of host threads (XLA-owned, kept for compatibility).",
        "-B \tnumber of BLAS threads (XLA-owned, kept for compatibility).",
        "-S \tnumber of device shards (XLA-owned, kept for compatibility).",
        "--compile-cache DIR \tpersistent JAX compilation cache",
        "\t(cold rounds reload compiled programs instead of recompiling).",
        "--corpus-cache DIR \tpacked corpus cache location (default:",
        "\ta dotfile next to each sample dir; HPNN_NO_CORPUS_CACHE=1 off).",
        "--corpus-cache-max-mb N \tLRU size cap on the --corpus-cache",
        "\tdir: least-recently-used packs past the cap are evicted (the",
        "\tin-flight run's pack never is; 0: no cap).",
        "--ckpt-dir DIR \tcheckpoint directory (default ./ckpt).",
        "--profile-dir DIR \tcapture the whole run as a jax.profiler",
        "\ttrace into DIR (TensorBoard-loadable; chip-side on TPU).",
        "--lnn native \topt into the native LNN regression kernel",
        "\t(linear output head + MSE objective) instead of the",
        "\treference's warn-and-fallthrough; HPNN_LNN_NATIVE=1 is the",
        "\tenv equivalent.  Default keeps reference byte parity.",
    ]
    if train:
        lines += [
            "--tile S \tbatched-tile convergence engine: train groups",
            "\tof S samples per GEMM-shaped step (per-lane convergence",
            "\tmasking; documented trajectory divergence vs per-sample",
            "\ttraining for S>1).  'auto' asks the topology autotuner",
            "\t(HPNN_NO_AUTOTUNE=1 disables; HPNN_AUTOTUNE_CACHE=DIR",
            "\trelocates the decision cache); 0 keeps per-sample mode.",
            "--epochs N \ttrain N epochs in-process (default 1); the",
            "\tseeded shuffle stream continues across epochs, and the",
            "\tcorpus + weights stay device-resident between them",
            "\t(HPNN_NO_EPOCH_PIPELINE=1 restages per epoch instead).",
            "--ckpt-every N \tsnapshot every N epoch boundaries (atomic,",
            "\twritten off the critical path; 0: only on exit/signal).",
            "--ckpt-keep N \tretention: keep last N snapshots + the",
            "\tbest-by-error one (0: keep all).",
            "--resume [PATH] \tcontinue bit-exactly from the latest",
            "\tsnapshot in PATH (a ckpt dir or bundle; default",
            "\t--ckpt-dir): weights, BPM momentum, shuffle-RNG state",
            "\tand epoch counter are restored.  Bundles are VERIFIED",
            "\tagainst their recorded sha256 fingerprints; a corrupt",
            "\tbundle falls back to the newest intact one.",
            "--replicate-to DEST \tship each verified snapshot bundle,",
            "\tcontent-addressed, to DEST (a directory, or",
            "\thttp://HOST:PORT of a mesh router); --resume restores",
            "\tfrom DEST when no local bundle survives.  Default:",
            "\t$HPNN_REPLICATE_TO.",
            "--model-parallel N \tshard every layer's neuron rows over",
            "\tN mesh devices (the reference's MPI_Allgather row split,",
            "\toverlapped ring schedule); wins over the conf [model]",
            "\tkeyword.  Composes with [batch] on a 2-D data x model",
            "\tmesh; HPNN_NO_TP_OVERLAP=1 falls back to whole-layer",
            "\tall-gathers.",
            "--trainer T \tselect the trainer from the registry:",
            "\t'cg' (batched nonlinear conjugate gradient,",
            "\tPolak-Ribiere + restart, on-device line search;",
            "\tHPNN_CG_ITERS iterations per epoch), 'bp', or 'bpm'.",
            "\tWins over the conf [train]/[trainer] keywords; CG",
            "\tstate (direction/gradient/restarts) rides snapshot",
            "\tbundles and resumes bit-exactly.",
        ]
    lines += [
        "***********************************",
        "input:     neural network .def file",
        "contains the network definition and",
        "topology. May contain weight values",
        "or context for a random generation.",
        "***********************************",
    ]
    return "\n".join(lines) + "\n"


_LONG_OPTS = {"--compile-cache": "compile_cache",
              "--corpus-cache": "corpus_cache",
              "--ckpt-dir": "ckpt_dir",
              "--profile-dir": "profile_dir",
              "--replicate-to": "replicate_to"}
# enumerated long options (value must be one of the listed choices).
# --lnn parses for BOTH train_nn and run_nn (the native regression head
# applies to eval too); --trainer is train_nn-only.
_LONG_CHOICE_OPTS = {"--lnn": ("lnn", ("native",), True),
                     "--trainer": ("trainer", ("cg", "bp", "bpm"), False)}
# integer-valued long options (value validated like the reference's
# numeric switches); min value enforced at parse time.  Most are
# train_nn-only; _SHARED_INT_OPTS also parse for run_nn.
_LONG_INT_OPTS = {"--epochs": ("epochs", 1),
                  "--ckpt-every": ("ckpt_every", 0),
                  "--ckpt-keep": ("ckpt_keep", 0),
                  "--corpus-cache-max-mb": ("corpus_cache_max_mb", 0),
                  "--tile": ("tile", 0),
                  "--model-parallel": ("model_parallel", 1)}
_SHARED_INT_OPTS = frozenset(("--corpus-cache-max-mb",))


def _parse_args(argv: list[str], name: str, train: bool):
    """Reference-style parse; returns (filename, verbose, extras) or None
    on -h, raises SystemExit(-1) on syntax errors.  ``extras`` holds the
    long options this rebuild adds on top of the reference grammar
    (--compile-cache/--corpus-cache/--ckpt-dir everywhere;
    --epochs/--ckpt-every/--ckpt-keep/--resume for train_nn, mirroring
    the checkpoint subsystem); anything else starting with ``--`` still
    errors like the reference parser."""
    filename = None
    extras = {v: None for v in _LONG_OPTS.values()}
    extras.update({v: None for v, _ in _LONG_INT_OPTS.values()})
    extras.update({v: None for v, _, _ in _LONG_CHOICE_OPTS.values()})
    extras["resume"] = None
    numeric = {"O": runtime.set_omp_threads, "B": runtime.set_omp_blas,
               "S": runtime.set_cuda_streams}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "-":
            # bare '-': the reference's switch loop sees ISGRAPH('\0') false
            # and silently ignores the argument (train_nn.c:86)
            i += 1
            continue
        key, eq, val = arg.partition("=")
        if key == "--resume" and train:
            # --resume [PATH]: the value is OPTIONAL (default: the ckpt
            # dir).  A separated token is taken as the path only when it
            # plausibly IS a checkpoint -- otherwise it is the trailing
            # conf filename ("train_nn --resume nn.conf" resumes from
            # ./ckpt and trains nn.conf).  --resume=PATH is explicit.
            if eq:
                if not val:
                    sys.stderr.write(
                        "syntax error: bad --resume parameter!\n")
                    sys.stdout.write(_help_text(name, train))
                    raise SystemExit(-1)
                extras["resume"] = val
            else:
                from .ckpt import looks_like_checkpoint

                nxt = argv[i + 1] if i + 1 < len(argv) else None
                if nxt and not nxt.startswith("-") \
                        and looks_like_checkpoint(nxt):
                    extras["resume"] = nxt
                    i += 1
                else:
                    extras["resume"] = True
            i += 1
            continue
        if key in _LONG_INT_OPTS and (train or key in _SHARED_INT_OPTS):
            dest, floor = _LONG_INT_OPTS[key]
            if not eq:
                i += 1
                val = argv[i] if i < len(argv) else ""
            if key == "--tile" and val.strip().lower() == "auto":
                # --tile auto: the measured autotuner decision
                extras[dest] = -1
                i += 1
                continue
            # GET_UINT-style: parse the leading digits (train_nn.c:124)
            digits = ""
            for ch in val:
                if not ch.isdigit():
                    break
                digits += ch
            if not digits or int(digits) < floor:
                sys.stderr.write(f"syntax error: bad {key} parameter!\n")
                sys.stdout.write(_help_text(name, train))
                raise SystemExit(-1)
            extras[dest] = int(digits)
            i += 1
            continue
        if key in _LONG_CHOICE_OPTS:
            dest, choices, shared = _LONG_CHOICE_OPTS[key]
            if train or shared:
                if not eq:
                    i += 1
                    val = argv[i] if i < len(argv) else ""
                if val.strip().lower() not in choices:
                    sys.stderr.write(
                        f"syntax error: bad {key} parameter!\n")
                    sys.stdout.write(_help_text(name, train))
                    raise SystemExit(-1)
                extras[dest] = val.strip().lower()
                i += 1
                continue
        if key in _LONG_OPTS:
            if not eq:
                i += 1
                val = argv[i] if i < len(argv) else ""
            if not val:
                sys.stderr.write(f"syntax error: bad {key} parameter!\n")
                sys.stdout.write(_help_text(name, train))
                raise SystemExit(-1)
            extras[_LONG_OPTS[key]] = val
            i += 1
            continue
        if arg.startswith("-"):
            j = 1
            while j < len(arg):
                c = arg[j]
                if c == "h":
                    sys.stdout.write(_help_text(name, train))
                    return None
                if c == "v":
                    # increment live so the third -v logs "verbosity set
                    # to 3." exactly like _NN(inc,verbose) (libhpnn.c:73)
                    nn_log.inc_verbosity()
                    j += 1
                    continue
                if c == "x" and train:
                    runtime.toggle_dry()  # no-op, as the reference
                    j += 1
                    continue
                if c in numeric:
                    if j + 1 < len(arg):
                        value = arg[j + 1:]
                    else:
                        i += 1
                        value = (argv[i] if i < len(argv) else "").lstrip()
                    # GET_UINT is atoi-style: parse the leading digits
                    # (train_nn.c:124); trailing junk is ignored
                    digits = ""
                    for ch in value:
                        if not ch.isdigit():
                            break
                        digits += ch
                    if not digits or int(digits) == 0:
                        sys.stderr.write(
                            f"syntax error: bad -{c} parameter!\n")
                        sys.stdout.write(_help_text(name, train))
                        raise SystemExit(-1)
                    numeric[c](int(digits))
                    break  # no combination after a numeric switch
                sys.stderr.write("syntax error: unrecognized option!\n")
                sys.stdout.write(_help_text(name, train))
                raise SystemExit(-1)
        else:
            if filename is not None:
                # second filename: the reference fails silently
                # (train_nn.c:199 `if(have_filename) goto FAIL;`)
                raise SystemExit(-1)
            filename = arg
        i += 1
    return filename or "./nn.conf", nn_log.get_verbosity(), extras


def _apply_extras(extras: dict) -> None:
    """Wire the long options into the runtime: an explicit flag wins over
    the HPNN_* env defaults init_all applied (same contract as serve_nn's
    --compile-cache)."""
    if extras.get("compile_cache"):
        runtime.enable_compilation_cache(extras["compile_cache"])
    if extras.get("corpus_cache"):
        from .io import corpus

        corpus.set_cache_dir(extras["corpus_cache"])
    if extras.get("corpus_cache_max_mb") is not None:
        from .io import corpus

        corpus.set_cache_max_mb(extras["corpus_cache_max_mb"])


def _dump_kernel_atomic(neural, path: str) -> None:
    """kernel.tmp/kernel.opt writes go through the crash-safe tmp +
    fsync + rename path (io.atomic) -- a kill mid-dump can no longer
    truncate a previously good kernel file."""
    from .io.kernel_io import dump_kernel_to_path

    dump_kernel_to_path(neural.kernel, path)


def train_nn_main(argv: list[str] | None = None) -> int:
    """train_nn (tests/train_nn.c:59-255), extended with the checkpoint
    subsystem: ``--epochs N`` multi-epoch training, ``--ckpt-every`` /
    ``--ckpt-dir`` / ``--ckpt-keep`` crash-safe snapshots off the
    critical path, and ``--resume [PATH]`` bit-exact continuation
    (hpnn_tpu/ckpt)."""
    from .utils.trace import phase

    argv = sys.argv[1:] if argv is None else argv
    with phase("init_all"):
        runtime.init_all(1)
    parsed = _parse_args(argv, "train_nn", train=True)
    if parsed is None:
        runtime.deinit_all()
        return 0
    filename, _verbose, extras = parsed
    _apply_extras(extras)
    from .obs.profiler import profile_run

    # --profile-dir D: the whole run (configure + train + dump) under a
    # jax.profiler capture; a start failure warns and runs unprofiled
    with profile_run(extras.get("profile_dir")):
        return _train_nn_body(filename, extras)


def _train_nn_body(filename: str, extras: dict) -> int:
    from .utils.trace import phase

    epochs = extras.get("epochs") or 1
    epochs_given = extras.get("epochs") is not None
    resume = extras.get("resume")
    ckpt_on = bool(resume or extras.get("ckpt_dir")
                   or extras.get("ckpt_every") is not None
                   or extras.get("ckpt_keep") is not None)
    ckpt_dir = extras.get("ckpt_dir") or "./ckpt"
    every = (extras["ckpt_every"] if extras.get("ckpt_every") is not None
             else 1)
    keep = extras.get("ckpt_keep") or 0
    with phase("configure"):
        neural = configure(filename)
    if neural is None:
        sys.stderr.write("FAILED to read NN configuration file! (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if extras.get("tile") is not None:
        # the CLI flag wins over a [tile] conf keyword
        neural.conf.tile = extras["tile"]
    if extras.get("lnn"):
        # --lnn native: opt into the native LNN regression head (wins
        # over a [lnn] conf keyword, like --tile over [tile])
        neural.conf.lnn = extras["lnn"]
    if extras.get("model_parallel") is not None:
        # --model-parallel N: row-sharding degree, wins over [model]
        neural.conf.model = extras["model_parallel"]
    if extras.get("trainer"):
        # --trainer cg|bp|bpm: select a registry trainer; coerces the
        # conf [train] type so snapshots/serve report coherently
        from .io.conf import NN_TRAIN_BP, NN_TRAIN_BPM, NN_TRAIN_CG

        t = extras["trainer"]
        neural.conf.trainer = t
        neural.conf.train = {"cg": NN_TRAIN_CG, "bpm": NN_TRAIN_BPM,
                             "bp": NN_TRAIN_BP}[t]
    replicate_to = extras.get("replicate_to") \
        or os.environ.get("HPNN_REPLICATE_TO") or None
    snap = None
    start_epoch = 0
    if resume:
        from .ckpt import load_snapshot

        resume_path = resume if isinstance(resume, str) else ckpt_dir
        snap = load_snapshot(resume_path)
        if snap is None and replicate_to:
            # the local checkpoint history is gone or wholly corrupt:
            # restore the newest intact REPLICATED bundle (ISSUE 14)
            # into the CHECKPOINT DIR and walk again.  Bundles ship
            # under scope_for(<ckpt dir>), so a --resume naming a
            # bundle dir (or a file inside one) must resolve to its
            # enclosing checkpoint dir both for the scope lookup and
            # as the restore target -- restoring INTO a bundle dir
            # would nest it where the candidate walk never looks
            from .ckpt import SNAPSHOT_STATE
            from .ckpt.replicate import restore_bundle, resolve_scope

            rdir = resume_path
            if os.path.isfile(rdir):
                rdir = os.path.dirname(rdir) or "."
            if os.path.isfile(os.path.join(rdir, SNAPSHOT_STATE)):
                rdir = os.path.dirname(os.path.abspath(rdir))
            if restore_bundle(replicate_to, resolve_scope(rdir),
                              rdir) is not None:
                snap = load_snapshot(rdir)
        if snap is None:
            sys.stderr.write("FAILED to resume: no loadable snapshot! "
                             "(ABORTING)\n")
            runtime.deinit_all()
            return -1
        if snap.topology != list(neural.kernel.params):
            sys.stderr.write(
                f"FAILED to resume: snapshot topology {snap.topology} "
                f"does not match the configured kernel "
                f"{list(neural.kernel.params)}! (ABORTING)\n")
            runtime.deinit_all()
            return -1
        from .parallel import coord

        if snap.world_size != coord.world_size():
            # ISSUE 18: the bundle is bit-exact only along the world
            # size that wrote it -- the shuffle stream is world-size
            # independent, but a resumed run's collectives, snapshot
            # barrier and rank-0 write discipline are not.  Refuse
            # loudly on EVERY rank instead of silently diverging.
            sys.stderr.write(
                f"FAILED to resume: snapshot {snap.tag} was written by "
                f"a {snap.world_size}-process run, but this run has "
                f"{coord.world_size()} process(es)! Relaunch with the "
                "matching HPNN_NUM_PROCESSES (or retrain). "
                "(ABORTING)\n")
            runtime.deinit_all()
            return -1
        # bit-exact restore: float64 weights from state.npz (NOT the
        # quantized text), the effective seed, and the epoch counter;
        # the shuffle-RNG words go to train_loop below.  BPM momentum
        # buffers ride the bundle too, but the update rule re-zeroes
        # them at every sample entry (ann_raz_momentum, ann.c:2391), so
        # restoring them is a no-op by construction.
        neural.kernel.weights = list(snap.weights)
        neural.conf.seed = snap.seed
        start_epoch = snap.epoch
        # native-trainer carry (CG direction/grad/restart counter):
        # restored so the resumed trajectory is bit-exact
        neural.trainer_state = snap.trainer_state
        if isinstance(resume, str) and not extras.get("ckpt_dir"):
            # an explicit --resume PATH names the run's checkpoint
            # home: continued snapshots go back THERE (the bundle's
            # parent = the manifest's directory), not to ./ckpt --
            # splitting one run's history across two dirs would strand
            # any --watch-ckpt server pointed at PATH
            ckpt_dir = os.path.dirname(snap.path)
        if not epochs_given and snap.target_epochs:
            # a bare --resume continues to the interrupted run's own
            # --epochs goal (recorded in the bundle) instead of
            # silently training zero epochs
            epochs = snap.target_epochs
        if start_epoch >= epochs:
            sys.stderr.write(
                f"CKPT: snapshot is already at epoch {start_epoch} of "
                f"{epochs}; nothing left to train (pass --epochs N to "
                "extend the run)\n")
    try:
        _dump_kernel_atomic(neural, "kernel.tmp")
    except OSError:
        sys.stderr.write("FAILED to open kernel.tmp for WRITE!\n")
        runtime.deinit_all()
        return -1
    if epochs > 1 or ckpt_on or start_epoch:
        from .ckpt import CheckpointManager, train_loop

        mgr = None
        if ckpt_on:
            mgr = CheckpointManager(ckpt_dir, every=every, keep_last=keep,
                                    target_epochs=epochs,
                                    replicate_to=replicate_to)
            if snap is not None:
                mgr.seed_errors(snap.errors)
        with phase("train_kernel"):
            trained, _interrupted = train_loop(
                neural, epochs, manager=mgr, start_epoch=start_epoch,
                rng_state=snap.rng_state if snap is not None else None)
    else:
        mgr = None
        with phase("train_kernel"):
            trained = train_kernel(neural)
    if not trained:
        sys.stderr.write("FAILED to train kernel!\n")
        runtime.deinit_all()
        return -1
    try:
        _dump_kernel_atomic(neural, "kernel.opt")
    except OSError:
        # the reference prints the kernel.tmp message on BOTH dump
        # failures (tests/train_nn.c:243) -- quirk preserved
        sys.stderr.write("FAILED to open kernel.tmp for WRITE!\n")
        runtime.deinit_all()
        return -1
    if mgr is not None:
        try:
            mgr.record_final("kernel.opt")
        except Exception as exc:
            sys.stderr.write(f"FAILED to publish checkpoint manifest: "
                             f"{exc}\n")
            runtime.deinit_all()
            return -1
    else:
        # plain (reference-mode) retrain: if a manifest from an earlier
        # checkpointed run tracks this exact kernel.opt, refresh its
        # fingerprint so run_nn's staleness guard stays truthful
        from .ckpt import refresh_final_kernel

        refresh_final_kernel(ckpt_dir, "kernel.opt")
    runtime.deinit_all()
    return 0


def run_nn_main(argv: list[str] | None = None) -> int:
    """run_nn (tests/run_nn.c:66-234)."""
    from .utils.trace import phase

    argv = sys.argv[1:] if argv is None else argv
    with phase("init_all"):
        runtime.init_all(1)
    parsed = _parse_args(argv, "run_nn", train=False)
    if parsed is None:
        runtime.deinit_all()
        return 0
    filename, _verbose, extras = parsed
    _apply_extras(extras)
    from .obs.profiler import profile_run

    with profile_run(extras.get("profile_dir")):
        return _run_nn_body(filename, extras)


def _run_nn_body(filename: str, extras: dict) -> int:
    from .utils.trace import phase

    with phase("configure"):
        neural = configure(filename)
    if neural is None:
        sys.stderr.write("FAILED to read NN configuration file! (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if extras.get("lnn"):
        neural.conf.lnn = extras["lnn"]
    if neural.conf.f_kernel:
        # staleness guard (checkpoint subsystem): when a manifest has a
        # recorded fingerprint for this exact kernel file and the bytes
        # no longer match, warn with both paths instead of silently
        # evaluating stale/modified weights
        ckpt_dir = extras.get("ckpt_dir") or "./ckpt"
        if os.path.isdir(ckpt_dir):
            from .ckpt import check_kernel_fingerprint

            check_kernel_fingerprint(neural.conf.f_kernel, ckpt_dir)
    with phase("run_kernel"):
        run_kernel(neural)
    runtime.deinit_all()
    return 0


def serve_nn_main(argv: list[str] | None = None) -> int:
    """serve_nn: long-lived inference server over the same ``.conf``
    files run_nn takes (hpnn_tpu.serve).  New subsystem, so the flag
    grammar is argparse rather than the reference parser -- there is no
    reference binary to stay byte-compatible with."""
    import argparse

    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(
        prog="serve_nn",
        description="serve trained hpnn kernels over HTTP "
                    "(POST /v1/kernels/<name>/infer)")
    ap.add_argument("confs", nargs="*", default=["./nn.conf"],
                    metavar="conf", help="nn.conf files (run_nn format; "
                    "default ./nn.conf); each registers one kernel")
    ap.add_argument("-v", "--verbose", action="count", default=0,
                    help="increase verbosity (repeatable)")
    ap.add_argument("-a", "--addr", default="127.0.0.1",
                    help="bind address (default 127.0.0.1)")
    ap.add_argument("-p", "--port", type=int, default=8080,
                    help="bind port; 0 picks an ephemeral one")
    ap.add_argument("-b", "--max-batch", type=int, default=64,
                    help="max rows per device launch / largest batch "
                    "bucket (default 64)")
    ap.add_argument("-q", "--queue-rows", type=int, default=256,
                    help="bounded queue capacity in rows; admission "
                    "beyond it is rejected with 429 (default 256)")
    ap.add_argument("--linger-ms", type=float, default=0.0,
                    help="wait this long after the first queued request "
                    "so concurrent clients can fill the batch (default "
                    "0: dispatch immediately)")
    ap.add_argument("--timeout-s", type=float, default=30.0,
                    help="default per-request deadline (default 30)")
    ap.add_argument("--parity", choices=("strict", "fast"),
                    default="strict",
                    help="serving tier: 'strict' answers bit-identically "
                    "to run_nn (default); 'fast' routes buckets >= "
                    "--fast-threshold to the GEMM/sharded throughput "
                    "path (dtype-accurate, ULP-level batch-shape "
                    "variation)")
    ap.add_argument("--fast-threshold", type=int, default=256,
                    help="smallest batch bucket the 'fast' parity tier "
                    "applies to (default 256; smaller buckets keep the "
                    "strict path)")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="shard 'fast' buckets over N devices on a data "
                    "mesh (0: single device; -1: all local devices; "
                    "capped to what is available)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent JAX compilation cache directory: "
                    "restart warmup reloads compiled buckets instead of "
                    "recompiling them")
    ap.add_argument("--warmup-mode",
                    choices=("background", "sync", "off"),
                    default="background",
                    help="bucket pre-compilation: 'background' (default) "
                    "binds the socket immediately and reports 'warming' "
                    "on /healthz until the compile cache is hot; 'sync' "
                    "warms before binding; 'off' skips warmup")
    ap.add_argument("--no-warmup", action="store_true",
                    help="alias for --warmup-mode off")
    ap.add_argument("--watch-ckpt", action="append", default=[],
                    metavar="[NAME=]DIR",
                    help="watch a checkpoint directory's manifest "
                    "(hpnn_tpu/ckpt) and hot-reload the named kernel on "
                    "every generation bump; NAME defaults to the only "
                    "registered kernel (repeatable)")
    ap.add_argument("--watch-interval", type=float, default=2.0,
                    metavar="S", help="manifest poll period in seconds "
                    "(default 2.0)")
    ap.add_argument("--jobs", type=int, default=0, metavar="N",
                    help="enable the online training service with an "
                    "N-job bounded queue (POST /v1/kernels/<name>/train; "
                    "0: disabled).  Scheduler workers time-slice the "
                    "device against eval traffic at epoch granularity "
                    "and hot-swap every epoch-boundary snapshot into "
                    "serving")
    ap.add_argument("--job-workers", type=int, default=None, metavar="K",
                    help="(with --jobs) concurrent training jobs: K "
                    "scheduler workers each pin their job to a disjoint "
                    "best-fit device slice of the mesh (submit params "
                    "dp_devices/tp_devices/model_parallel size the ask; "
                    "undeclared jobs share the mesh evenly).  Default: "
                    "$HPNN_JOB_WORKERS or 1 (the single-worker "
                    "whole-mesh behavior)")
    ap.add_argument("--job-dir", default="./jobs", metavar="DIR",
                    help="persistent job state/corpus/checkpoint root "
                    "(default ./jobs); a restarted server reports the "
                    "directory's job history")
    ap.add_argument("--job-auto-resume", action="store_true",
                    default=False,
                    help="(with --jobs) lease-based auto-resume: on "
                    "start and on a supervisor tick, interrupted and "
                    "expired-lease jobs are re-queued from their "
                    "newest VERIFIED local-or-replicated bundle, "
                    "bounded by HPNN_JOB_MAX_RETRIES with jittered "
                    "backoff, then failed with a reason.  Default: "
                    "$HPNN_JOB_AUTO_RESUME=1")
    ap.add_argument("--replicate-to", default=None, metavar="DEST",
                    help="(with --jobs) off-host checkpoint "
                    "replication: every verified snapshot bundle is "
                    "shipped content-addressed to DEST (a directory, "
                    "or http://HOST:PORT of a mesh router storing it "
                    "in its blob store); auto-resume restores from "
                    "DEST when the local dir is lost.  Default: "
                    "$HPNN_REPLICATE_TO")
    ap.add_argument("--ab-fraction", type=float, default=0.0,
                    metavar="F",
                    help="A/B generation pinning: during a hot swap this "
                    "fraction of unpinned traffic keeps routing to the "
                    "previous weights generation until the job's "
                    "promote/rollback endpoint finalizes (0: every swap "
                    "is immediate; X-HPNN-Generation pins per request "
                    "either way)")
    ap.add_argument("--auth-token", default=None, metavar="TOKEN",
                    help="require this bearer token (or X-HPNN-Token) on "
                    "every mutating endpoint: reload, train submits, job "
                    "actions, profile captures.  Default: "
                    "$HPNN_SERVE_TOKEN; unset = open")
    ap.add_argument("--trace", action="store_true", default=False,
                    help="enable span tracing + the flight recorder "
                    "(GET /v1/debug/trace; every infer request gets a "
                    "trace id, X-HPNN-Trace-Id honored/echoed).  "
                    "Default: $HPNN_TRACE; off costs nothing")
    ap.add_argument("--trace-sample", type=float, default=None,
                    metavar="P",
                    help="head-based trace sampling: keep each new "
                    "trace with probability P (decided once at trace "
                    "birth; an explicit X-HPNN-Trace-Id or a high-QoS "
                    "request always captures; dropped requests take "
                    "the zero-allocation no-trace path).  Default: "
                    "$HPNN_TRACE_SAMPLE, else keep everything")
    ap.add_argument("--span-dir", default=None, metavar="DIR",
                    help="durable span export: stream recorded spans "
                    "into rotating NDJSON segments under DIR "
                    "(fsync-on-rotate, size/age retention via "
                    "HPNN_SPAN_* knobs), so traces survive SIGKILL; "
                    "GET /v1/debug/trace?spool=1 reads them back.  "
                    "Default: $HPNN_SPAN_DIR, else ring-only")
    ap.add_argument("--shed-low", action="store_true", default=False,
                    help="SLO-driven load shedding: while an "
                    "--slo-* error budget is burning, reject LOW-lane "
                    "(X-HPNN-Priority: low) traffic at admission with "
                    "429 + Retry-After; clears after HPNN_SHED_CLEAR_S "
                    "of quiet (hysteresis).  Default: $HPNN_SHED=1")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="destination for POST /v1/debug/profile "
                    "jax.profiler captures (default: a fresh temp dir "
                    "per capture)")
    ap.add_argument("--mesh-role", choices=("router", "worker",
                                            "standby"),
                    default=None,
                    help="multi-host serve mesh: 'router' fans infer "
                    "requests over registered worker hosts (no local "
                    "compute; /healthz warms until --workers N are "
                    "live); 'worker' serves normally AND registers "
                    "with --router (heartbeat + generation catch-up); "
                    "'standby' passively mirrors --primary and takes "
                    "over routing when the primary's health checks "
                    "flatline")
    ap.add_argument("--router", default=None, metavar="HOST:PORT",
                    help="the router to register with (required for "
                    "--mesh-role worker)")
    ap.add_argument("--standby", default=None, metavar="HOST:PORT",
                    help="(router) advertise this standby address in "
                    "every registration ack, so worker heartbeats "
                    "fail over to it when this router dies")
    ap.add_argument("--primary", default=None, metavar="HOST:PORT",
                    help="(standby) the primary router to mirror and "
                    "take over from (required for --mesh-role "
                    "standby)")
    ap.add_argument("--takeover-after", type=int, default=None,
                    metavar="N",
                    help="(standby) consecutive unreachable mirror "
                    "polls before takeover (default "
                    "$HPNN_MESH_TAKEOVER_AFTER or 3)")
    ap.add_argument("--router-token", default=None, metavar="TOKEN",
                    help="spill-protection token routers stamp on "
                    "dispatch RPCs (X-HPNN-Router) and workers learn "
                    "from the registration ack.  Default: "
                    "$HPNN_MESH_ROUTER_TOKEN, else a random "
                    "per-process one -- router PAIRS should share an "
                    "explicit token (or an --auth-token, which lets "
                    "the standby mirror it)")
    ap.add_argument("--require-router", action="store_true",
                    default=False,
                    help="(worker) only serve infer traffic bearing "
                    "the router's X-HPNN-Router token (403 otherwise) "
                    "-- router-enforced per-client quotas cannot be "
                    "bypassed by direct worker hits.  Default: "
                    "$HPNN_MESH_REQUIRE_ROUTER=1")
    ap.add_argument("--advertise", default=None, metavar="HOST:PORT",
                    help="address the router should reach THIS worker "
                    "at (default: 127.0.0.1:<bound port>)")
    ap.add_argument("--workers", type=int, default=1, metavar="N",
                    help="router quorum: /healthz reports 'warming' "
                    "until N workers are live (default 1)")
    ap.add_argument("--mesh-health-interval", type=float, default=1.0,
                    metavar="S",
                    help="router worker health-check poll period "
                    "(default 1.0s; ejection after "
                    "HPNN_MESH_EJECT_AFTER consecutive misses)")
    ap.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                    help="(router) elastic worker lifecycle: a "
                    "supervisor drives the hpnn_serve_desired_workers "
                    "gauge by spawning/retiring local serve_nn worker "
                    "subprocesses within [MIN, MAX] (drain-then-"
                    "SIGTERM on retire; HPNN_AUTOSCALE_EXEC replaces "
                    "the subprocess actions for real fleets)")
    ap.add_argument("--autoscale-cooldown", type=float, default=None,
                    metavar="S",
                    help="minimum seconds between autoscale actions "
                    "(default $HPNN_AUTOSCALE_COOLDOWN_S or 30)")
    ap.add_argument("--auto-promote", action="store_true",
                    default=False,
                    help="(with --jobs) eval-driven promotion: when a "
                    "training job finishes, evaluate its candidate "
                    "generation vs the pre-job baseline on a held-out "
                    "test dir (the submit's 'test_samples' or the "
                    "conf's [test_dir]) and promote-if-better / roll "
                    "back on regression, recording the A/B generation "
                    "counters as canary evidence")
    ap.add_argument("--quota-rows", type=float, default=0.0, metavar="F",
                    help="per-client token-bucket quota in rows/sec "
                    "(keyed by X-HPNN-Client, the auth token, or the "
                    "peer address; over-quota requests get 429 with a "
                    "refill-derived Retry-After; 0: no quota)")
    ap.add_argument("--quota-burst", type=float, default=None,
                    metavar="N",
                    help="quota bucket burst capacity in rows "
                    "(default: max(2 x rate, 64))")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    metavar="F",
                    help="latency SLO: at most 1%% of completed "
                    "requests may exceed F ms.  Enables per-kernel "
                    "error-budget burn-rate gauges in /metrics and a "
                    "structured slo_burn event when the fast AND slow "
                    "windows (HPNN_SLO_FAST_S/HPNN_SLO_SLOW_S) both "
                    "burn past HPNN_SLO_BURN (default 14.4).  Unset: "
                    "no SLO tracking, zero cost")
    ap.add_argument("--slo-availability", type=float, default=None,
                    metavar="F",
                    help="availability SLO target in [0, 1) (e.g. "
                    "0.999): server-caused failures (HTTP >= 500) "
                    "spend the 1-F error budget; same burn-rate "
                    "gauges/alerts as --slo-p99-ms")
    args = ap.parse_args(argv)

    from .serve.server import ServeApp, make_server
    from .utils.trace import phase

    for _ in range(args.verbose):
        nn_log.inc_verbosity()
    with phase("init_all"):
        runtime.init_all(nn_log.get_verbosity())
    nn_log.set_verbosity(args.verbose)
    if args.compile_cache:
        # explicit flag: wins over HPNN_* env defaults applied by
        # init_all, so restart warmup hits the on-disk cache
        runtime.enable_compilation_cache(args.compile_cache)
    warmup_mode = "off" if args.no_warmup else args.warmup_mode
    if not 0.0 <= args.ab_fraction <= 1.0:
        sys.stderr.write(f"--ab-fraction must be in [0, 1]: "
                         f"{args.ab_fraction} (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if args.mesh_role == "worker" and not args.router:
        sys.stderr.write("--mesh-role worker requires --router "
                         "HOST:PORT (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if args.mesh_role == "standby" and not args.primary:
        sys.stderr.write("--mesh-role standby requires --primary "
                         "HOST:PORT (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if args.slo_availability is not None \
            and not 0.0 <= args.slo_availability < 1.0:
        sys.stderr.write(f"--slo-availability must be in [0, 1): "
                         f"{args.slo_availability} (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if args.slo_p99_ms is not None and args.slo_p99_ms <= 0.0:
        sys.stderr.write(f"--slo-p99-ms must be > 0: "
                         f"{args.slo_p99_ms} (ABORTING)\n")
        runtime.deinit_all()
        return -1
    if args.trace_sample is not None \
            and not 0.0 <= args.trace_sample <= 1.0:
        sys.stderr.write(f"--trace-sample must be in [0, 1]: "
                         f"{args.trace_sample} (ABORTING)\n")
        runtime.deinit_all()
        return -1
    autoscale_bounds = None
    if args.autoscale is not None:
        if args.mesh_role != "router":
            sys.stderr.write("--autoscale requires --mesh-role router "
                             "(ABORTING)\n")
            runtime.deinit_all()
            return -1
        lo, sep, hi = args.autoscale.partition(":")
        if not (sep and lo.isdigit() and hi.isdigit()
                and int(lo) <= int(hi) and int(hi) >= 1):
            sys.stderr.write(f"--autoscale must be MIN:MAX with "
                             f"0 <= MIN <= MAX, MAX >= 1: "
                             f"{args.autoscale!r} (ABORTING)\n")
            runtime.deinit_all()
            return -1
        autoscale_bounds = (int(lo), int(hi))
    auth_token = args.auth_token or os.environ.get("HPNN_SERVE_TOKEN") \
        or None
    router_token = args.router_token \
        or os.environ.get("HPNN_MESH_ROUTER_TOKEN") or None
    require_router = args.require_router \
        or os.environ.get("HPNN_MESH_REQUIRE_ROUTER") == "1"
    # name this process's mesh role for post-mortem dump files
    # (trace-<reason>-<role>-<pid>.ndjson): a killed fleet's dumps must
    # be tellable apart without opening them
    from .obs import trace as _obs_trace

    _obs_trace.set_role(args.mesh_role or "local")
    app = ServeApp(max_batch=args.max_batch,
                   max_queue_rows=args.queue_rows,
                   linger_s=args.linger_ms / 1e3,
                   default_timeout_s=args.timeout_s,
                   parity=args.parity,
                   fast_threshold=args.fast_threshold,
                   mesh_devices=(None if args.mesh < 0 else args.mesh),
                   auth_token=auth_token,
                   ab_fraction=args.ab_fraction,
                   trace=args.trace or None,
                   profile_dir=args.profile_dir,
                   quota_rows=args.quota_rows,
                   quota_burst=args.quota_burst,
                   slo_p99_ms=args.slo_p99_ms,
                   slo_availability=args.slo_availability,
                   require_router=require_router,
                   trace_sample=args.trace_sample,
                   span_dir=args.span_dir,
                   shed_low=args.shed_low or None)
    if args.mesh_role == "router":
        # before add_model: batchers are wired to the worker pool at
        # creation.  (A router never computes locally -- add_model
        # itself skips warmup when a mesh router is enabled, so no
        # warmup_mode override is needed here.)
        app.enable_mesh_router(
            required_workers=max(1, args.workers),
            health_interval_s=args.mesh_health_interval,
            standby_addr=args.standby,
            router_token=router_token)
        sby = f", standby {args.standby}" if args.standby else ""
        sys.stdout.write(f"SERVE: mesh router (quorum "
                         f"{max(1, args.workers)} worker(s); workers "
                         f"register via POST /v1/mesh/register{sby})\n")
    elif args.mesh_role == "standby":
        # a full mesh router held passive: mirrors --primary and takes
        # over when its health checks flatline
        app.enable_mesh_standby(
            args.primary,
            required_workers=max(1, args.workers),
            health_interval_s=args.mesh_health_interval,
            router_token=router_token,
            takeover_after=args.takeover_after)
        sys.stdout.write(f"SERVE: mesh standby (mirroring "
                         f"{args.primary}; takeover after "
                         f"{app.mesh_standby.takeover_after} missed "
                         "polls)\n")
    n_ok = 0
    for conf in args.confs:
        with phase("register"):
            model = app.add_model(conf, warmup=warmup_mode != "off",
                                  background=warmup_mode == "background")
        if model is None:
            sys.stderr.write(
                f"FAILED to load NN configuration file {conf}! "
                "(skipping)\n")
        else:
            n_ok += 1
    if n_ok == 0:
        sys.stderr.write("no kernel could be registered (ABORTING)\n")
        runtime.deinit_all()
        return -1
    for spec in args.watch_ckpt:
        wname, eq, wdir = spec.partition("=")
        if not eq:
            wname, wdir = "", wname
        if not wname:
            names = app.registry.names()
            if len(names) != 1:
                sys.stderr.write(
                    f"--watch-ckpt {spec}: NAME= is required when "
                    f"{len(names)} kernels are registered (ABORTING)\n")
                runtime.deinit_all()
                return -1
            wname = names[0]
        if app.registry.get(wname) is None:
            sys.stderr.write(f"--watch-ckpt: unknown kernel '{wname}' "
                             "(ABORTING)\n")
            runtime.deinit_all()
            return -1
        app.watch_manifest(wname, wdir, interval_s=args.watch_interval)
    if args.jobs > 0:
        from .utils.env import env_int

        app.enable_jobs(args.job_dir, capacity=args.jobs,
                        auto_promote=args.auto_promote,
                        auto_resume=args.job_auto_resume or None,
                        replicate_to=args.replicate_to,
                        job_workers=args.job_workers
                        or env_int("HPNN_JOB_WORKERS", 1, lo=1))
        tok = "on" if auth_token else "OFF (pass --auth-token)"
        promo = ", auto-promote" if args.auto_promote else ""
        res = ", auto-resume" if app.jobs.auto_resume else ""
        rep = (f", replicate-to={app.jobs.replicate_to}"
               if app.jobs.replicate_to else "")
        wrk = (f", workers={app.jobs.workers} over "
               f"{app.jobs.slices.n} device(s)"
               if app.jobs.workers > 1 else "")
        sys.stdout.write(f"SERVE: online training enabled "
                         f"(queue={args.jobs}, job-dir={args.job_dir}, "
                         f"ab-fraction={args.ab_fraction:g}, "
                         f"auth={tok}{promo}{res}{rep}{wrk})\n")
    elif args.auto_promote:
        sys.stderr.write("serve: --auto-promote is inert without "
                         "--jobs N (ignored)\n")
    httpd = make_server(args.addr, args.port, app)
    host, port = httpd.server_address[:2]
    if app.mesh_standby is not None:
        # runtime re-pairing (ISSUE 14): the mirror polls advertise
        # this standby's own address, so a surviving ACTIVE router
        # adopts it and re-advertises the pair to workers
        app.mesh_standby.advertise = args.advertise \
            or f"127.0.0.1:{port}"
    if autoscale_bounds is not None:
        # after the bind: spawned workers register against THIS
        # router's real port
        worker_args = ["--parity", args.parity,
                       "--fast-threshold", str(args.fast_threshold),
                       "-b", str(args.max_batch),
                       "-q", str(args.queue_rows)]
        if args.trace:
            worker_args.append("--trace")
        if args.trace_sample is not None:
            worker_args += ["--trace-sample", str(args.trace_sample)]
        app.enable_autoscale(
            f"127.0.0.1:{port}", [c for c in args.confs],
            min_workers=autoscale_bounds[0],
            max_workers=autoscale_bounds[1],
            cooldown_s=args.autoscale_cooldown,
            worker_args=tuple(worker_args))
        sys.stdout.write(
            f"SERVE: autoscale supervisor on "
            f"[{autoscale_bounds[0]}, {autoscale_bounds[1]}] workers "
            f"(cooldown {app.autoscaler.cooldown_s:g}s)\n")
    if args.mesh_role == "worker":
        # register AFTER the socket is bound (the advertised default
        # needs the real port) but before serve_forever: the heartbeat
        # loop retries until the router is reachable
        from .serve.mesh.worker import WorkerAgent

        advertise = args.advertise or f"127.0.0.1:{port}"
        app.mesh_worker = WorkerAgent(app, args.router,
                                      advertise).start()
        app.metrics.set_swarm_source(app.mesh_worker.swarm_snapshot)
        sys.stdout.write(f"SERVE: mesh worker (router {args.router}, "
                         f"advertising {advertise})\n")
    # unconditional: the bound port is the serving contract (with -p 0
    # it is the only way a launcher learns where to point clients)
    sys.stdout.write(f"SERVE: listening on http://{host}:{port}\n")
    sys.stdout.flush()
    # graceful drain (jobs satellite): SIGTERM/SIGINT stop the accept
    # loop; the finally block then finishes the in-flight training
    # epoch, snapshots, marks the job `interrupted` (resumable) and
    # drains the eval batchers -- nothing admitted is dropped.
    # shutdown() must run OFF this thread (it joins serve_forever).
    import signal as _signal
    import threading as _threading

    def _drain_signal(signum, frame):
        sys.stdout.write("SERVE: draining...\n")
        sys.stdout.flush()
        _threading.Thread(target=httpd.shutdown, daemon=True).start()

    prev_handlers = {}
    for _sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            prev_handlers[_sig] = _signal.signal(_sig, _drain_signal)
        except (ValueError, OSError):  # pragma: no cover - non-main thread
            pass
    # flight-recorder post-mortem (ISSUE 8): on SIGTERM/SIGINT drain or
    # a fault escaping serve_forever, the span ring is dumped as NDJSON
    # next to the job dir (or the cwd when jobs are off) -- the last
    # window of activity survives the process
    dump_dir = args.job_dir if args.jobs > 0 else "."
    dumped = False

    def _collected_worker_spans():
        """A router's post-mortem must carry its last collected worker
        spans -- the remote halves of in-flight traces die with the
        process otherwise (ISSUE 10 bugfix)."""
        if app.mesh_router is None:
            return None
        try:
            return app.mesh_router.fleet.collected_spans()
        except Exception:  # pragma: no cover - post-mortem best effort
            return None

    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover - handler owns SIGINT
        sys.stdout.write("SERVE: draining...\n")
        sys.stdout.flush()
    except Exception:
        from .obs import trace as obs_trace

        path = obs_trace.dump_to_dir(
            dump_dir, reason="fault",
            extra_spans=_collected_worker_spans())
        dumped = True  # ONE post-mortem per process, fault-tagged
        if path:
            sys.stderr.write(f"SERVE: flight recorder dumped to "
                             f"{path}\n")
        raise
    finally:
        for _sig, old in prev_handlers.items():
            try:
                _signal.signal(_sig, old)
            except (ValueError, OSError):  # pragma: no cover
                pass
        httpd.shutdown()
        app.close(drain=True)
        if not dumped:
            from .obs import trace as obs_trace

            if app.span_exporter is not None:
                # the durable spool IS the post-mortem: app.close()
                # already flushed + rotated every span (drain-phase
                # ones included) into finalized segments -- a second
                # ad-hoc dump file would just duplicate them
                from .obs.export import list_segments

                segs = list_segments(app.span_exporter.span_dir)
                path = segs[-1] if segs else None
            else:
                path = obs_trace.dump_to_dir(
                    dump_dir, reason="shutdown",
                    extra_spans=_collected_worker_spans())
            if path:
                sys.stdout.write(f"SERVE: flight recorder dumped to "
                                 f"{path}\n")
                sys.stdout.flush()
        runtime.deinit_all()
    return 0


def train_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(train_nn_main())


def run_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(run_nn_main())


def serve_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(serve_nn_main())
