"""train_nn / run_nn command-line drivers.

Flag-compatible rebuilds of the reference demo binaries
(``/root/reference/tests/train_nn.c``, ``tests/run_nn.c``):

    train_nn [-h] [-v]... [-x] [-O n] [-B n] [-S n]
             [--compile-cache DIR] [--corpus-cache DIR] [conf]
    run_nn   [-h] [-v]... [-O n] [-B n] [-S n]
             [--compile-cache DIR] [--corpus-cache DIR] [conf]

* flags combine (``-vvv``) and -O/-B/-S accept attached (``-O4``) or
  separated (``-O 4``) values, like the reference parser
  (``train_nn.c:100-199``); the ``--compile-cache``/``--corpus-cache``
  long options are rebuild extensions (persistent XLA program cache and
  packed-corpus cache location, mirroring ``serve_nn``);
* the conf file defaults to ``./nn.conf`` (``train_nn.c:215``);
* train_nn dumps the untrained kernel to ``kernel.tmp`` before training and
  the trained kernel to ``kernel.opt`` after (``train_nn.c:224-243``) --
  the checkpoint/resume workflow the tutorials build on.
"""

from __future__ import annotations

import sys

from . import runtime
from .api import configure, dump_kernel_def, run_kernel, train_kernel
from .utils import nn_log


def _help_text(name: str, train: bool) -> str:
    lines = [
        "***********************************",
        f"usage:  {name} [-options] [input]",
        "***********************************",
        "options:",
        "-h \tdisplay this help;",
        "-v \tincrease verbosity;",
    ]
    if train:
        lines.append("-x \tdiscard results.")
    lines += [
        "-O \tnumber of host threads (XLA-owned, kept for compatibility).",
        "-B \tnumber of BLAS threads (XLA-owned, kept for compatibility).",
        "-S \tnumber of device shards (XLA-owned, kept for compatibility).",
        "--compile-cache DIR \tpersistent JAX compilation cache",
        "\t(cold rounds reload compiled programs instead of recompiling).",
        "--corpus-cache DIR \tpacked corpus cache location (default:",
        "\ta dotfile next to each sample dir; HPNN_NO_CORPUS_CACHE=1 off).",
        "***********************************",
        "input:     neural network .def file",
        "contains the network definition and",
        "topology. May contain weight values",
        "or context for a random generation.",
        "***********************************",
    ]
    return "\n".join(lines) + "\n"


_LONG_OPTS = {"--compile-cache": "compile_cache",
              "--corpus-cache": "corpus_cache"}


def _parse_args(argv: list[str], name: str, train: bool):
    """Reference-style parse; returns (filename, verbose, extras) or None
    on -h, raises SystemExit(-1) on syntax errors.  ``extras`` holds the
    long options this rebuild adds on top of the reference grammar
    (--compile-cache/--corpus-cache, mirroring serve_nn); anything else
    starting with ``--`` still errors like the reference parser."""
    filename = None
    extras = {v: None for v in _LONG_OPTS.values()}
    numeric = {"O": runtime.set_omp_threads, "B": runtime.set_omp_blas,
               "S": runtime.set_cuda_streams}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "-":
            # bare '-': the reference's switch loop sees ISGRAPH('\0') false
            # and silently ignores the argument (train_nn.c:86)
            i += 1
            continue
        key, eq, val = arg.partition("=")
        if key in _LONG_OPTS:
            if not eq:
                i += 1
                val = argv[i] if i < len(argv) else ""
            if not val:
                sys.stderr.write(f"syntax error: bad {key} parameter!\n")
                sys.stdout.write(_help_text(name, train))
                raise SystemExit(-1)
            extras[_LONG_OPTS[key]] = val
            i += 1
            continue
        if arg.startswith("-"):
            j = 1
            while j < len(arg):
                c = arg[j]
                if c == "h":
                    sys.stdout.write(_help_text(name, train))
                    return None
                if c == "v":
                    # increment live so the third -v logs "verbosity set
                    # to 3." exactly like _NN(inc,verbose) (libhpnn.c:73)
                    nn_log.inc_verbosity()
                    j += 1
                    continue
                if c == "x" and train:
                    runtime.toggle_dry()  # no-op, as the reference
                    j += 1
                    continue
                if c in numeric:
                    if j + 1 < len(arg):
                        value = arg[j + 1:]
                    else:
                        i += 1
                        value = (argv[i] if i < len(argv) else "").lstrip()
                    # GET_UINT is atoi-style: parse the leading digits
                    # (train_nn.c:124); trailing junk is ignored
                    digits = ""
                    for ch in value:
                        if not ch.isdigit():
                            break
                        digits += ch
                    if not digits or int(digits) == 0:
                        sys.stderr.write(
                            f"syntax error: bad -{c} parameter!\n")
                        sys.stdout.write(_help_text(name, train))
                        raise SystemExit(-1)
                    numeric[c](int(digits))
                    break  # no combination after a numeric switch
                sys.stderr.write("syntax error: unrecognized option!\n")
                sys.stdout.write(_help_text(name, train))
                raise SystemExit(-1)
        else:
            if filename is not None:
                # second filename: the reference fails silently
                # (train_nn.c:199 `if(have_filename) goto FAIL;`)
                raise SystemExit(-1)
            filename = arg
        i += 1
    return filename or "./nn.conf", nn_log.get_verbosity(), extras


def _apply_extras(extras: dict) -> None:
    """Wire the long options into the runtime: an explicit flag wins over
    the HPNN_* env defaults init_all applied (same contract as serve_nn's
    --compile-cache)."""
    if extras.get("compile_cache"):
        runtime.enable_compilation_cache(extras["compile_cache"])
    if extras.get("corpus_cache"):
        from .io import corpus

        corpus.set_cache_dir(extras["corpus_cache"])


def train_nn_main(argv: list[str] | None = None) -> int:
    """train_nn (tests/train_nn.c:59-255)."""
    from .utils.trace import phase

    argv = sys.argv[1:] if argv is None else argv
    with phase("init_all"):
        runtime.init_all(1)
    parsed = _parse_args(argv, "train_nn", train=True)
    if parsed is None:
        runtime.deinit_all()
        return 0
    filename, _verbose, extras = parsed
    _apply_extras(extras)
    with phase("configure"):
        neural = configure(filename)
    if neural is None:
        sys.stderr.write("FAILED to read NN configuration file! (ABORTING)\n")
        runtime.deinit_all()
        return -1
    try:
        with open("kernel.tmp", "w") as fp:
            dump_kernel_def(neural, fp)
    except OSError:
        sys.stderr.write("FAILED to open kernel.tmp for WRITE!\n")
        runtime.deinit_all()
        return -1
    with phase("train_kernel"):
        trained = train_kernel(neural)
    if not trained:
        sys.stderr.write("FAILED to train kernel!\n")
        runtime.deinit_all()
        return -1
    try:
        with open("kernel.opt", "w") as fp:
            dump_kernel_def(neural, fp)
    except OSError:
        sys.stderr.write("FAILED to open kernel.tmp for WRITE!\n")
        runtime.deinit_all()
        return -1
    runtime.deinit_all()
    return 0


def run_nn_main(argv: list[str] | None = None) -> int:
    """run_nn (tests/run_nn.c:66-234)."""
    from .utils.trace import phase

    argv = sys.argv[1:] if argv is None else argv
    with phase("init_all"):
        runtime.init_all(1)
    parsed = _parse_args(argv, "run_nn", train=False)
    if parsed is None:
        runtime.deinit_all()
        return 0
    filename, _verbose, extras = parsed
    _apply_extras(extras)
    with phase("configure"):
        neural = configure(filename)
    if neural is None:
        sys.stderr.write("FAILED to read NN configuration file! (ABORTING)\n")
        runtime.deinit_all()
        return -1
    with phase("run_kernel"):
        run_kernel(neural)
    runtime.deinit_all()
    return 0


def serve_nn_main(argv: list[str] | None = None) -> int:
    """serve_nn: long-lived inference server over the same ``.conf``
    files run_nn takes (hpnn_tpu.serve).  New subsystem, so the flag
    grammar is argparse rather than the reference parser -- there is no
    reference binary to stay byte-compatible with."""
    import argparse

    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(
        prog="serve_nn",
        description="serve trained hpnn kernels over HTTP "
                    "(POST /v1/kernels/<name>/infer)")
    ap.add_argument("confs", nargs="*", default=["./nn.conf"],
                    metavar="conf", help="nn.conf files (run_nn format; "
                    "default ./nn.conf); each registers one kernel")
    ap.add_argument("-v", "--verbose", action="count", default=0,
                    help="increase verbosity (repeatable)")
    ap.add_argument("-a", "--addr", default="127.0.0.1",
                    help="bind address (default 127.0.0.1)")
    ap.add_argument("-p", "--port", type=int, default=8080,
                    help="bind port; 0 picks an ephemeral one")
    ap.add_argument("-b", "--max-batch", type=int, default=64,
                    help="max rows per device launch / largest batch "
                    "bucket (default 64)")
    ap.add_argument("-q", "--queue-rows", type=int, default=256,
                    help="bounded queue capacity in rows; admission "
                    "beyond it is rejected with 429 (default 256)")
    ap.add_argument("--linger-ms", type=float, default=0.0,
                    help="wait this long after the first queued request "
                    "so concurrent clients can fill the batch (default "
                    "0: dispatch immediately)")
    ap.add_argument("--timeout-s", type=float, default=30.0,
                    help="default per-request deadline (default 30)")
    ap.add_argument("--parity", choices=("strict", "fast"),
                    default="strict",
                    help="serving tier: 'strict' answers bit-identically "
                    "to run_nn (default); 'fast' routes buckets >= "
                    "--fast-threshold to the GEMM/sharded throughput "
                    "path (dtype-accurate, ULP-level batch-shape "
                    "variation)")
    ap.add_argument("--fast-threshold", type=int, default=256,
                    help="smallest batch bucket the 'fast' parity tier "
                    "applies to (default 256; smaller buckets keep the "
                    "strict path)")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="shard 'fast' buckets over N devices on a data "
                    "mesh (0: single device; -1: all local devices; "
                    "capped to what is available)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent JAX compilation cache directory: "
                    "restart warmup reloads compiled buckets instead of "
                    "recompiling them")
    ap.add_argument("--warmup-mode",
                    choices=("background", "sync", "off"),
                    default="background",
                    help="bucket pre-compilation: 'background' (default) "
                    "binds the socket immediately and reports 'warming' "
                    "on /healthz until the compile cache is hot; 'sync' "
                    "warms before binding; 'off' skips warmup")
    ap.add_argument("--no-warmup", action="store_true",
                    help="alias for --warmup-mode off")
    args = ap.parse_args(argv)

    from .serve.server import ServeApp, make_server
    from .utils.trace import phase

    for _ in range(args.verbose):
        nn_log.inc_verbosity()
    with phase("init_all"):
        runtime.init_all(nn_log.get_verbosity())
    nn_log.set_verbosity(args.verbose)
    if args.compile_cache:
        # explicit flag: wins over HPNN_* env defaults applied by
        # init_all, so restart warmup hits the on-disk cache
        runtime.enable_compilation_cache(args.compile_cache)
    warmup_mode = "off" if args.no_warmup else args.warmup_mode
    app = ServeApp(max_batch=args.max_batch,
                   max_queue_rows=args.queue_rows,
                   linger_s=args.linger_ms / 1e3,
                   default_timeout_s=args.timeout_s,
                   parity=args.parity,
                   fast_threshold=args.fast_threshold,
                   mesh_devices=(None if args.mesh < 0 else args.mesh))
    n_ok = 0
    for conf in args.confs:
        with phase("register"):
            model = app.add_model(conf, warmup=warmup_mode != "off",
                                  background=warmup_mode == "background")
        if model is None:
            sys.stderr.write(
                f"FAILED to load NN configuration file {conf}! "
                "(skipping)\n")
        else:
            n_ok += 1
    if n_ok == 0:
        sys.stderr.write("no kernel could be registered (ABORTING)\n")
        runtime.deinit_all()
        return -1
    httpd = make_server(args.addr, args.port, app)
    host, port = httpd.server_address[:2]
    # unconditional: the bound port is the serving contract (with -p 0
    # it is the only way a launcher learns where to point clients)
    sys.stdout.write(f"SERVE: listening on http://{host}:{port}\n")
    sys.stdout.flush()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        sys.stdout.write("SERVE: draining...\n")
        sys.stdout.flush()
    finally:
        httpd.shutdown()
        app.close(drain=True)
        runtime.deinit_all()
    return 0


def train_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(train_nn_main())


def run_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(run_nn_main())


def serve_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(serve_nn_main())
