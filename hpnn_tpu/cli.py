"""train_nn / run_nn command-line drivers.

Flag-compatible rebuilds of the reference demo binaries
(``/root/reference/tests/train_nn.c``, ``tests/run_nn.c``):

    train_nn [-h] [-v]... [-x] [-O n] [-B n] [-S n] [conf]
    run_nn   [-h] [-v]... [-O n] [-B n] [-S n] [conf]

* flags combine (``-vvv``) and -O/-B/-S accept attached (``-O4``) or
  separated (``-O 4``) values, like the reference parser
  (``train_nn.c:100-199``);
* the conf file defaults to ``./nn.conf`` (``train_nn.c:215``);
* train_nn dumps the untrained kernel to ``kernel.tmp`` before training and
  the trained kernel to ``kernel.opt`` after (``train_nn.c:224-243``) --
  the checkpoint/resume workflow the tutorials build on.
"""

from __future__ import annotations

import sys

from . import runtime
from .api import configure, dump_kernel_def, run_kernel, train_kernel
from .utils import nn_log


def _help_text(name: str, train: bool) -> str:
    lines = [
        "***********************************",
        f"usage:  {name} [-options] [input]",
        "***********************************",
        "options:",
        "-h \tdisplay this help;",
        "-v \tincrease verbosity;",
    ]
    if train:
        lines.append("-x \tdiscard results.")
    lines += [
        "-O \tnumber of host threads (XLA-owned, kept for compatibility).",
        "-B \tnumber of BLAS threads (XLA-owned, kept for compatibility).",
        "-S \tnumber of device shards (XLA-owned, kept for compatibility).",
        "***********************************",
        "input:     neural network .def file",
        "contains the network definition and",
        "topology. May contain weight values",
        "or context for a random generation.",
        "***********************************",
    ]
    return "\n".join(lines) + "\n"


def _parse_args(argv: list[str], name: str, train: bool):
    """Reference-style parse; returns (filename, verbose) or None on -h,
    raises SystemExit(-1) on syntax errors."""
    filename = None
    numeric = {"O": runtime.set_omp_threads, "B": runtime.set_omp_blas,
               "S": runtime.set_cuda_streams}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "-":
            # bare '-': the reference's switch loop sees ISGRAPH('\0') false
            # and silently ignores the argument (train_nn.c:86)
            i += 1
            continue
        if arg.startswith("-"):
            j = 1
            while j < len(arg):
                c = arg[j]
                if c == "h":
                    sys.stdout.write(_help_text(name, train))
                    return None
                if c == "v":
                    # increment live so the third -v logs "verbosity set
                    # to 3." exactly like _NN(inc,verbose) (libhpnn.c:73)
                    nn_log.inc_verbosity()
                    j += 1
                    continue
                if c == "x" and train:
                    runtime.toggle_dry()  # no-op, as the reference
                    j += 1
                    continue
                if c in numeric:
                    if j + 1 < len(arg):
                        value = arg[j + 1:]
                    else:
                        i += 1
                        value = (argv[i] if i < len(argv) else "").lstrip()
                    # GET_UINT is atoi-style: parse the leading digits
                    # (train_nn.c:124); trailing junk is ignored
                    digits = ""
                    for ch in value:
                        if not ch.isdigit():
                            break
                        digits += ch
                    if not digits or int(digits) == 0:
                        sys.stderr.write(
                            f"syntax error: bad -{c} parameter!\n")
                        sys.stdout.write(_help_text(name, train))
                        raise SystemExit(-1)
                    numeric[c](int(digits))
                    break  # no combination after a numeric switch
                sys.stderr.write("syntax error: unrecognized option!\n")
                sys.stdout.write(_help_text(name, train))
                raise SystemExit(-1)
        else:
            if filename is not None:
                # second filename: the reference fails silently
                # (train_nn.c:199 `if(have_filename) goto FAIL;`)
                raise SystemExit(-1)
            filename = arg
        i += 1
    return filename or "./nn.conf", nn_log.get_verbosity()


def train_nn_main(argv: list[str] | None = None) -> int:
    """train_nn (tests/train_nn.c:59-255)."""
    from .utils.trace import phase

    argv = sys.argv[1:] if argv is None else argv
    with phase("init_all"):
        runtime.init_all(1)
    parsed = _parse_args(argv, "train_nn", train=True)
    if parsed is None:
        runtime.deinit_all()
        return 0
    filename, _verbose = parsed
    with phase("configure"):
        neural = configure(filename)
    if neural is None:
        sys.stderr.write("FAILED to read NN configuration file! (ABORTING)\n")
        runtime.deinit_all()
        return -1
    try:
        with open("kernel.tmp", "w") as fp:
            dump_kernel_def(neural, fp)
    except OSError:
        sys.stderr.write("FAILED to open kernel.tmp for WRITE!\n")
        runtime.deinit_all()
        return -1
    with phase("train_kernel"):
        trained = train_kernel(neural)
    if not trained:
        sys.stderr.write("FAILED to train kernel!\n")
        runtime.deinit_all()
        return -1
    try:
        with open("kernel.opt", "w") as fp:
            dump_kernel_def(neural, fp)
    except OSError:
        sys.stderr.write("FAILED to open kernel.tmp for WRITE!\n")
        runtime.deinit_all()
        return -1
    runtime.deinit_all()
    return 0


def run_nn_main(argv: list[str] | None = None) -> int:
    """run_nn (tests/run_nn.c:66-234)."""
    from .utils.trace import phase

    argv = sys.argv[1:] if argv is None else argv
    with phase("init_all"):
        runtime.init_all(1)
    parsed = _parse_args(argv, "run_nn", train=False)
    if parsed is None:
        runtime.deinit_all()
        return 0
    filename, _verbose = parsed
    with phase("configure"):
        neural = configure(filename)
    if neural is None:
        sys.stderr.write("FAILED to read NN configuration file! (ABORTING)\n")
        runtime.deinit_all()
        return -1
    with phase("run_kernel"):
        run_kernel(neural)
    runtime.deinit_all()
    return 0


def train_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(train_nn_main())


def run_nn_entry() -> None:  # console_scripts hook
    raise SystemExit(run_nn_main())
