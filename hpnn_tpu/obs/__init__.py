"""Observability subsystem: structured span tracing, an in-process
flight recorder, and on-demand device profiling (ISSUE 8 tentpole).

Three layers, stacked so each can be used without the next:

* :mod:`.trace` -- spans.  ``span("name")`` times a region and records
  it (name, trace id, parent, monotonic start, duration, attributes)
  into a bounded ring buffer -- the *flight recorder* -- that is
  dumpable as NDJSON at any time (``GET /v1/debug/trace`` on a live
  server, or :func:`dump_to_dir` from a signal handler).  Tracing is
  OFF by default and the off path is a single global ``is None`` check
  returning a shared no-op singleton -- zero allocation, so the serving
  hot path pays nothing when idle.
* the serve/train drivers thread trace CONTEXT through their hot paths:
  a serve request's trace id (``X-HPNN-Trace-Id``, or generated) links
  the HTTP handler's spans to the batcher's and the registry's even
  though they run on different threads (explicit ``trace_id``/
  ``parent_id`` on :func:`record`); training epochs nest their phases
  through the thread-local span stack.
* :mod:`.profiler` -- ``jax.profiler`` wrapped for one-shot live
  captures (``POST /v1/debug/profile``) and whole-run captures
  (``train_nn --profile-dir D``), so a chip-side XLA trace can be
  pulled from a running server without restarting it.
* :mod:`.slo` -- per-kernel availability/latency objectives with
  multi-window error-budget burn rates (ISSUE 10): ``--slo-p99-ms`` /
  ``--slo-availability`` construct a :class:`slo.SloTracker`, /metrics
  exports the burn gauges, and a structured ``slo_burn`` event fires
  when the fast AND slow windows both exceed the threshold.

``HPNN_TRACE=1`` enables tracing at ``init_all`` / server start;
``HPNN_TRACE_BUFFER=N`` sizes the ring (default 8192 spans).  Spans
carry a monotone ``seq`` for incremental cross-host collection
(``/v1/debug/trace?since_seq=N``), and :func:`set_role` names the
process's mesh role in auto-dump filenames.

Production hardening (ISSUE 13): ``HPNN_TRACE_SAMPLE=p`` /
``--trace-sample`` keeps tracing on at fleet QPS by deciding keep/drop
ONCE at trace birth (explicit trace ids and high-QoS requests force
capture), and :mod:`.export` ships recorded spans through a bounded
background spool into rotating NDJSON segments (``--span-dir``) so
post-hoc analysis survives SIGKILL.
"""

# --- structured event-name registry (ISSUE 15 satellite) --------------------
# Every ``nn_event``/``mesh_event`` name emitted anywhere in the tree,
# declared HERE with the subsystem category the incident timeline files
# it under.  A source-scanning test (tests/test_trace_analytics.py)
# fails on any literal event name missing from this table, so the
# timeline's event -> category mapping can never silently rot: adding
# an event means declaring it.  (``mesh_event`` names emit with the
# ``mesh_`` prefix -- declare the prefixed form.)
EVENT_NAMES: dict[str, str] = {
    # serve hot path
    "slow_request": "serve",
    # SLO error-budget burn (obs/slo.py)
    "slo_burn": "slo",
    "slo_burn_cleared": "slo",
    # checkpoint verification / resume fallback (ckpt/)
    "ckpt_fallback": "ckpt",
    # online-training jobs lifecycle (jobs/)
    "job_lease_expired": "jobs",
    "job_auto_resume": "jobs",
    "job_auto_resume_failed": "jobs",
    "job_slice_granted": "jobs",
    "job_slice_reclaimed": "jobs",
    "auto_promote": "jobs",
    # mesh lifecycle (serve/mesh/, emitted via mesh_event)
    "mesh_worker_registered": "mesh",
    "mesh_worker_readmitted": "mesh",
    "mesh_worker_retiring": "mesh",
    "mesh_worker_removed": "mesh",
    "mesh_worker_ejected": "mesh",
    "mesh_worker_router_switch": "mesh",
    "mesh_worker_catch_up": "mesh",
    "mesh_failover_retry": "mesh",
    "mesh_reload_broadcast": "mesh",
    "mesh_bundle_replicated": "mesh",
    "mesh_standby_mirror": "standby",
    "mesh_standby_takeover": "standby",
    "mesh_standby_attached": "standby",
    "mesh_shed_engaged": "slo",
    "mesh_shed_cleared": "slo",
    "mesh_autoscale_spawn": "autoscale",
    "mesh_autoscale_retire": "autoscale",
    "mesh_autoscale_confirmed": "autoscale",
    "mesh_autoscale_unconfirmed": "autoscale",
    "mesh_autoscale_reaped": "autoscale",
}

from .trace import (  # noqa: F401
    current_ctx,
    disable,
    dump_ndjson,
    dump_to_dir,
    enable,
    enable_from_env,
    enabled,
    get_exporter,
    get_role,
    last_seq,
    new_span_id,
    new_trace_id,
    record,
    render_ndjson,
    ring_id,
    sample_stats,
    sample_trace,
    set_exporter,
    set_role,
    set_sample_rate,
    snapshot,
    span,
)

__all__ = [
    "current_ctx", "disable", "dump_ndjson", "dump_to_dir", "enable",
    "enable_from_env", "enabled", "get_exporter", "get_role",
    "last_seq", "new_span_id", "new_trace_id", "record",
    "render_ndjson", "ring_id", "sample_stats", "sample_trace",
    "set_exporter", "set_role", "set_sample_rate", "snapshot", "span",
    "EVENT_NAMES",
]
