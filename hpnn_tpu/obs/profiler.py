"""On-demand device profiling: ``jax.profiler`` behind a process lock.

Two consumers:

* ``POST /v1/debug/profile {"seconds": N}`` on a live server --
  :func:`capture` starts an XLA/TSL trace, sleeps N seconds on the
  HTTP handler thread (the device keeps serving; the profiler observes
  from the side), stops, and reports the artifact directory.  One
  capture at a time process-wide: the underlying profiler is a global
  singleton, so a second concurrent start would abort it.
* ``train_nn/serve_nn --profile-dir D`` -- :func:`profile_run` wraps a
  whole run (started after init, stopped in the CLI's finally).

The captured directory is TensorBoard-loadable (``plugins/profile``)
and on TPU includes the chip-side trace; on CPU hosts it still records
host/XLA activity, so the plumbing is testable off-chip.

``jax.profiler`` availability is probed at call time and failures are
reported as :class:`ProfilerUnavailable` -- the serving layer maps it
to an HTTP status instead of a traceback, and a CLI run warns and
continues untraced (profiling is an observation, never a reason to
fail the run).
"""

from __future__ import annotations

import contextlib
import threading
import time

_lock = threading.Lock()
_active: dict | None = None

# bound a live-server capture: a forgotten 1e9-second profile must not
# pin the (singleton) profiler forever
MAX_CAPTURE_S = 300.0


class ProfilerUnavailable(RuntimeError):
    """jax.profiler could not start (missing dep / backend refusal)."""


class ProfilerBusy(RuntimeError):
    """A capture is already running (the profiler is a singleton)."""


def _start_trace(out_dir: str) -> None:
    try:
        import jax


        jax.profiler.start_trace(out_dir)
    except Exception as exc:  # noqa: BLE001 -- anything here means "no
        # profile", and the caller chose between 501 and a warning
        raise ProfilerUnavailable(
            f"jax.profiler failed to start: {type(exc).__name__}: {exc}")


def _stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


def active() -> dict | None:
    """The in-flight capture's public record, or None."""
    with _lock:
        if _active is None:
            return None
        return {k: v for k, v in _active.items()
                if not k.startswith("_")}


def start(out_dir: str) -> dict:
    """Begin a capture into ``out_dir``; raises ProfilerBusy /
    ProfilerUnavailable."""
    global _active
    with _lock:
        if _active is not None:
            raise ProfilerBusy(
                f"profile already running into {_active['dir']}")
        # "started" is a display/persist timestamp (wall); the elapsed
        # math in stop() uses the monotonic anchor
        _active = {"dir": out_dir, "started": time.time(),
                   "_mono": time.monotonic()}
    try:
        _start_trace(out_dir)
    except BaseException:
        with _lock:
            _active = None
        raise
    return active()


def stop() -> dict:
    """End the in-flight capture; returns its record (raises
    ProfilerUnavailable when none is running)."""
    global _active
    with _lock:
        rec = _active
    if rec is None:
        raise ProfilerUnavailable("no profile is running")
    try:
        _stop_trace()
    finally:
        with _lock:
            _active = None
    mono0 = rec.get("_mono")
    rec = {k: v for k, v in rec.items() if not k.startswith("_")}
    rec["seconds"] = round(time.monotonic() - mono0, 3) \
        if mono0 is not None else 0.0
    return rec


def capture(seconds: float, out_dir: str) -> dict:
    """One-shot capture: start, sleep ``seconds`` (clamped to
    ``MAX_CAPTURE_S``), stop.  Blocking -- the debug endpoint runs it on
    the request's own handler thread."""
    seconds = min(max(0.0, float(seconds)), MAX_CAPTURE_S)
    start(out_dir)
    try:
        time.sleep(seconds)
    finally:
        rec = stop()
    return rec


@contextlib.contextmanager
def profile_run(out_dir: str | None):
    """Whole-run capture for the CLIs (``--profile-dir D``); a None dir
    is a no-op so call sites stay unconditional.  Start failures warn
    and run unprofiled; the stop is best-effort on the way out."""
    if not out_dir:
        yield
        return
    try:
        start(out_dir)
    except (ProfilerBusy, ProfilerUnavailable) as exc:
        from ..utils.nn_log import nn_warn

        nn_warn(f"profile: {exc}; run continues unprofiled\n")
        yield
        return
    try:
        yield
    finally:
        with contextlib.suppress(Exception):
            stop()
