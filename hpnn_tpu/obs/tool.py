"""Offline trace-analytics tool over any span spool (ISSUE 15
tentpole, part 4)::

    python -m hpnn_tpu.obs.tool index    --span-dir D
    python -m hpnn_tpu.obs.tool search   --span-dir D [--kernel K]
        [--trace ID] [--min-ms F] [--status S] [--since T] [--until T]
        [--limit N]
    python -m hpnn_tpu.obs.tool critical --span-dir D [--kernel K]
        [--window S] [--limit N]
    python -m hpnn_tpu.obs.tool timeline --span-dir D [--since T]
        [--until T] [--limit N]

True post-mortem: the fleet can be GONE.  ``search``, ``critical`` and
``timeline`` run the SAME code the live endpoints run over the same
directory, so their stdout is byte-identical to the corresponding
``GET /v1/debug/trace/search`` / ``.../critical`` / ``...?timeline=1``
response bodies (pinned in tests/test_trace_analytics.py) -- an
incident review six weeks later reproduces exactly what the on-call
saw.  ``index`` builds (or repairs) every finalized segment's sidecar
up front, so the first interactive query doesn't pay the back-fill.

Exit codes: 0 on success (including an empty result), 2 on a bad
query, 1 when the span dir is missing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _add_common(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--span-dir", required=True, metavar="DIR",
                    help="the --span-dir a serve_nn/train run spooled "
                    "spans into (rotated segments + open spools)")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hpnn_tpu.obs.tool",
        description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_index = sub.add_parser(
        "index", help="build/repair every finalized segment's sidecar")
    _add_common(p_index)

    p_search = sub.add_parser(
        "search", help="per-trace summaries from the sidecar indexes")
    _add_common(p_search)
    p_search.add_argument("--kernel", default=None)
    p_search.add_argument("--trace", default=None)
    p_search.add_argument("--min-ms", default=None)
    p_search.add_argument("--status", default=None)
    p_search.add_argument("--since", default=None)
    p_search.add_argument("--until", default=None)
    p_search.add_argument("--limit", default=None)

    p_crit = sub.add_parser(
        "critical", help="aggregated critical-path phase attribution")
    _add_common(p_crit)
    p_crit.add_argument("--kernel", default=None)
    p_crit.add_argument("--window", default=None,
                        help="only traces starting in the trailing "
                        "WINDOW seconds (default: all)")
    p_crit.add_argument("--limit", default=None,
                        help="newest-N traces analyzed (default "
                        "HPNN_TRACE_CRITICAL_TRACES)")

    p_tl = sub.add_parser(
        "timeline", help="the merged incident timeline (NDJSON)")
    _add_common(p_tl)
    p_tl.add_argument("--since", default=None)
    p_tl.add_argument("--until", default=None)
    p_tl.add_argument("--limit", default=None)

    args = ap.parse_args(argv)
    span_dir = args.span_dir
    if not os.path.isdir(span_dir):
        sys.stderr.write(f"span dir not found: {span_dir}\n")
        return 1

    from . import analyze
    from . import index as trace_index
    from .export import list_segments, read_spool

    try:
        if args.cmd == "index":
            built = repaired = spans = 0
            trace_ids: set = set()
            segs = list_segments(span_dir)
            for seg in segs:
                had = trace_index.load_index(seg) is not None
                stale = (not had
                         and os.path.exists(trace_index.index_path(seg)))
                idx = trace_index.ensure_index(seg)
                if idx is None:
                    continue
                if not had:
                    if stale:
                        repaired += 1
                    else:
                        built += 1
                # unique ids: a trace routinely spans segments
                trace_ids.update(idx["traces"])
                spans += sum(t.get("spans", 0)
                             for t in idx["traces"].values())
            sys.stdout.write(json.dumps(
                {"span_dir": os.path.abspath(span_dir),
                 "segments": len(segs), "built": built,
                 "repaired": repaired, "traces": len(trace_ids),
                 "spans": spans}) + "\n")
            return 0
        if args.cmd == "search":
            payload = trace_index.search(span_dir, {
                "kernel": args.kernel, "trace": args.trace,
                "min_ms": args.min_ms, "status": args.status,
                "since": args.since, "until": args.until,
                "limit": args.limit})
            sys.stdout.write(json.dumps(payload) + "\n")
            return 0
        if args.cmd == "critical":
            payload = analyze.critical_from_dir(
                span_dir, kernel=args.kernel,
                window_s=float(args.window)
                if args.window is not None else None,
                limit=int(args.limit)
                if args.limit is not None else None)
            sys.stdout.write(json.dumps(payload) + "\n")
            return 0
        # timeline
        entries = analyze.build_timeline(
            read_spool(span_dir),
            since=float(args.since) if args.since is not None else None,
            until=float(args.until) if args.until is not None else None,
            limit=int(args.limit) if args.limit is not None else None)
        sys.stdout.write(analyze.render_timeline(entries))
        return 0
    except (TypeError, ValueError) as exc:
        sys.stderr.write(f"bad query: {exc}\n")
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
