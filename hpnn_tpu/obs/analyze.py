"""Critical-path attribution + the black-box incident timeline
(ISSUE 15 tentpole, parts 2-3).

The spool/ring give us span TREES; this module turns them into the two
answers an operator actually asks for:

* **which phase owns the latency?** -- :func:`critical_path` walks one
  trace's span tree backward from the latest-finishing span, always
  descending into the child that finished last, and charges each
  phase its SELF time along that path (the gap no child covers).
  :func:`critical_report` aggregates over many traces into per-phase
  p50/p99 self-time and the share of the p99 each phase owns
  ("queue_wait owns 61% of p99") -- the report ``GET
  /v1/debug/trace/critical`` serves.
* **what happened, in order?** -- :func:`build_timeline` merges spans,
  structured events (``mesh_*``/``slo_burn``/``ckpt_fallback``/
  autoscale -- recorded as zero-duration spans under the ``mesh``/
  ``events`` trace ids), and job state transitions (``job.state``
  spans) into one time-ordered view, so a takeover or shed incident
  reads as a single narrative (``GET /v1/debug/trace?timeline=1`` and
  ``obs.tool timeline``).

Cross-host stitching: a worker's half of a traced request arrives as a
SECOND root under the same trace id (the RPC carries the trace id, not
a span parent).  :func:`build_tree` re-parents such orphan roots under
the smallest enclosing span from another host -- the router's
``device_launch`` window that physically contained the RPC -- so the
critical path descends into the remote tree and the router's phase is
charged only for what the worker did NOT account for (queueing,
network, injected latency).  Timestamps across hosts share wall-clock
anchoring; containment uses a small slack (``_CLOCK_SLACK_S``) and
self-times clip at zero, so modest skew degrades attribution gracefully
instead of producing negative time.
"""

from __future__ import annotations

import math

# cross-host containment slack: wall anchors on two processes of one
# fleet disagree by clock-read jitter, not leap seconds
_CLOCK_SLACK_S = 0.005


def _start(s: dict) -> float:
    return s.get("ts", 0.0) or 0.0


def _end(s: dict) -> float:
    return _start(s) + (s.get("dur_s", 0.0) or 0.0)


def build_tree(spans: list[dict]) -> tuple[list[dict],
                                           dict[str, list[dict]]]:
    """(roots, children-by-span-id) for ONE trace's spans, deduplicated
    by span id.  Orphan roots (no parent, or a parent id the dump never
    caught) from a DIFFERENT host are re-parented under the smallest
    span that encloses them in time -- the cross-host stitch."""
    by_id: dict[str, dict] = {}
    for s in spans:
        sid = s.get("span")
        if sid:
            by_id.setdefault(sid, s)
    uniq = list(by_id.values())
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in uniq:
        parent = s.get("parent")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    if len(roots) > 1:
        stitched = []
        for r in sorted(roots, key=_end):
            host = r.get("host")
            best = None
            for c in uniq:
                if c is r or c.get("host") == host:
                    continue
                if (_start(c) - _CLOCK_SLACK_S <= _start(r)
                        and _end(r) <= _end(c) + _CLOCK_SLACK_S):
                    if best is None or (_end(c) - _start(c)
                                        < _end(best) - _start(best)):
                        best = c
            if best is not None:
                children.setdefault(best["span"], []).append(r)
            else:
                stitched.append(r)
        roots = stitched
    _nest_contained_siblings(children)
    for kids in children.values():
        kids.sort(key=_start)
    roots.sort(key=_start)
    return roots, children


# sibling-containment epsilon: spans recorded from the same timestamp
# pair land within the dump's 1e-6 ts rounding of each other
_SIBLING_EPS_S = 5e-5


def _nest_contained_siblings(children: dict[str, list[dict]]) -> None:
    """Re-parent a span under the smallest STRICTLY-LONGER sibling
    whose interval contains it.  The batcher records a remote batch's
    ``mesh.route`` (the whole worker-RPC window) as a SIBLING of the
    ``device_launch``/``d2h`` segments it temporally contains; nesting
    them makes the critical path descend through the RPC window into
    the worker's stitched tree instead of charging ``mesh.route`` for
    time the worker accounted for.  Strictly-longer keeps the relation
    acyclic; local sequential phase spans (disjoint intervals) are
    untouched."""
    for parent in list(children):
        kids = children[parent]
        if len(kids) < 2:
            continue
        moved: dict[int, dict] = {}
        for i, c in enumerate(kids):
            c_dur = _end(c) - _start(c)
            best = None
            for s in kids:
                s_dur = _end(s) - _start(s)
                if s is c or s_dur <= c_dur:
                    continue
                if (_start(s) - _SIBLING_EPS_S <= _start(c)
                        and _end(c) <= _end(s) + _SIBLING_EPS_S):
                    if best is None or s_dur < (_end(best)
                                                - _start(best)):
                        best = s
            if best is not None:
                moved[i] = best
        if not moved:
            continue
        children[parent] = [c for i, c in enumerate(kids)
                            if i not in moved]
        for i, target in moved.items():
            children.setdefault(target["span"], []).append(kids[i])


def critical_path(spans: list[dict]) -> list[tuple[dict, float]]:
    """The trace's critical path as ``[(span, self_seconds), ...]``
    outermost first.  At each span the walk moves backward from the
    span's end: the child that finished last (and had started by the
    cursor) is on the path and is descended into; the stretches no
    such child covers are the span's SELF time -- the time that phase,
    and nothing underneath it, was the reason the trace wasn't done."""
    roots, children = build_tree(spans)
    if not roots:
        return []
    # the path starts at the root that finished last: that end IS the
    # trace's completion
    root = max(roots, key=_end)
    path: list[tuple[dict, float]] = []

    def walk(span: dict) -> None:
        kids = children.get(span.get("span") or "", [])
        cursor = _end(span)
        lo = _start(span)
        self_s = 0.0
        descend: list[dict] = []
        while True:
            cand = None
            for c in kids:
                if _start(c) >= cursor:
                    continue
                if cand is None or _end(c) > _end(cand):
                    cand = c
            if cand is None or _end(cand) <= lo:
                break
            gap = cursor - min(_end(cand), cursor)
            if gap > 0:
                self_s += gap
            descend.append(cand)
            cursor = _start(cand)
            if cursor <= lo:
                break
        if cursor > lo:
            self_s += cursor - lo
        path.append((span, max(self_s, 0.0)))
        for c in descend:
            walk(c)

    walk(root)
    return path


def phase_self_times(spans: list[dict]) -> dict[str, float]:
    """Per-phase (span name) self seconds along ONE trace's critical
    path; multiple same-name spans on the path fold together."""
    out: dict[str, float] = {}
    for span, self_s in critical_path(spans):
        name = span.get("name") or "?"
        out[name] = out.get(name, 0.0) + self_s
    return out


def _percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over pre-sorted values (deterministic,
    no interpolation -- byte-stable across live and offline runs)."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(p / 100.0 * len(sorted_vals)))
    return sorted_vals[rank - 1]


def critical_report(traces: list[list[dict]], kernel: str | None,
                    window_s: float | None,
                    min_spans: int = 2) -> dict:
    """Aggregate critical-path attribution over many traces -- the
    ``/v1/debug/trace/critical`` payload.  Traces with fewer than
    ``min_spans`` spans carry no phase structure and are skipped (a
    lone root tells us the total, not who owns it)."""
    per_phase: dict[str, list[float]] = {}
    totals: list[float] = []
    analyzed = 0
    for spans in traces:
        if len(spans) < min_spans:
            continue
        phases = phase_self_times(spans)
        if not phases:
            continue
        analyzed += 1
        totals.append(sum(phases.values()))
        for name, self_s in phases.items():
            per_phase.setdefault(name, []).append(self_s)
    totals.sort()
    report_phases: dict[str, dict] = {}
    for name in sorted(per_phase):
        per_phase[name].sort()
    p99s = {name: _percentile(vals, 99.0)
            for name, vals in per_phase.items()}
    p99_sum = sum(p99s.values())
    for name in sorted(per_phase):
        vals = per_phase[name]
        p99 = p99s[name]
        report_phases[name] = {
            "count": len(vals),
            "p50_self_ms": round(_percentile(vals, 50.0) * 1e3, 3),
            "p99_self_ms": round(p99 * 1e3, 3),
            # this phase's slice of the p99 critical path: the number
            # the MFU/serve benches rank optimization targets by
            "share_p99": round(p99 / p99_sum, 4) if p99_sum > 0
            else 0.0,
        }
    top = max(report_phases,
              key=lambda n: report_phases[n]["p99_self_ms"],
              default=None)
    out = {
        "kernel": kernel,
        "window_s": window_s,
        "traces_analyzed": analyzed,
        "critical_ms": {
            "p50": round(_percentile(totals, 50.0) * 1e3, 3),
            "p99": round(_percentile(totals, 99.0) * 1e3, 3),
        },
        "phases": report_phases,
        "top_phase": top,
    }
    return out


_DEFAULT_CRITICAL_TRACES = 256


def _critical_trace_budget(limit: int | None) -> int:
    if limit is not None:
        return int(limit)
    from ..utils.env import env_int

    return env_int("HPNN_TRACE_CRITICAL_TRACES",
                   _DEFAULT_CRITICAL_TRACES, lo=1)


def critical_from_dir(span_dir: str, kernel: str | None = None,
                      window_s: float | None = None,
                      limit: int | None = None) -> dict:
    """The ``/v1/debug/trace/critical`` payload computed from a span
    spool on disk -- the live endpoint (with ``--span-dir``) and
    ``obs.tool critical`` both call THIS, so a post-mortem reproduces
    the live answer byte-for-byte."""
    import time

    from . import index as trace_index

    params: dict = {"limit": _critical_trace_budget(limit)}
    if kernel:
        params["kernel"] = kernel
    if window_s is not None:
        # span ts are wall_base-anchored persisted stamps
        params["since"] = time.time() - window_s  # vs wall_base ts
    rows = trace_index.search(span_dir, params)["traces"]
    by_trace = trace_index.fetch_traces(span_dir,
                                        [r["trace"] for r in rows])
    traces = [by_trace[r["trace"]] for r in rows
              if r["trace"] in by_trace]
    return critical_report(traces, kernel or None, window_s)


def critical_from_spans(spans: list[dict],
                        kernel: str | None = None,
                        window_s: float | None = None,
                        limit: int | None = None) -> dict:
    """The same payload over in-memory spans (ring + fleet store) --
    what a server WITHOUT a span spool answers from."""
    import time

    from . import index as trace_index

    params: dict = {"limit": _critical_trace_budget(limit)}
    if kernel:
        params["kernel"] = kernel
    if window_s is not None:
        # span ts are wall_base-anchored persisted stamps
        params["since"] = time.time() - window_s  # vs wall_base ts
    rows = trace_index.search_spans(spans, params)["traces"]
    wanted = {r["trace"] for r in rows}
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        tid = s.get("trace")
        if tid in wanted:
            by_trace.setdefault(tid, []).append(s)
    return critical_report(list(by_trace.values()), kernel or None,
                           window_s)


# --- incident timeline ------------------------------------------------------

def _event_category(name: str) -> str | None:
    """Timeline category for a span name, via the event-name registry
    (``obs.EVENT_NAMES``): ``event.<n>``/``mesh.<n>`` spans map back to
    their declared structured-event names; ``job.state`` spans are the
    jobs lifecycle."""
    from . import EVENT_NAMES

    if name == "job.state":
        return "jobs"
    if name.startswith("event."):
        return EVENT_NAMES.get(name[len("event."):], "event")
    if name.startswith("mesh."):
        return EVENT_NAMES.get("mesh_" + name[len("mesh."):], "mesh")
    return None


_ENTRY_ATTR_SKIP = frozenset((
    "name", "trace", "span", "parent", "ts", "dur_s", "thread", "seq"))


def build_timeline(spans: list[dict], since: float | None = None,
                   until: float | None = None,
                   limit: int | None = None) -> list[dict]:
    """The incident timeline: every event span (mesh lifecycle,
    structured ``nn_event``s, job state transitions) plus every ROOT
    span (requests, job runs, training epochs) as one time-ordered
    list of entries.  Child phase spans are deliberately folded away --
    the timeline is the narrative, ``?trace=ID`` is the microscope."""
    entries: list[dict] = []
    seen: set = set()
    for s in spans:
        if not isinstance(s, dict):
            continue
        name = s.get("name") or "?"
        if name == "trace.truncated":
            continue  # merger bookkeeping, not an incident event
        category = _event_category(name)
        is_root = s.get("parent") is None
        if category is None and not is_root:
            continue
        ts = s.get("ts", 0.0) or 0.0
        if since is not None and ts < since:
            continue
        if until is not None and ts > until:
            continue
        key = s.get("span") or (name, ts)
        if key in seen:
            continue
        seen.add(key)
        entry = {
            "ts": round(ts, 6),
            "kind": category or "span",
            "name": name,
            "trace": s.get("trace"),
        }
        if s.get("dur_s"):
            entry["dur_ms"] = round(s["dur_s"] * 1e3, 3)
        if s.get("host") is not None:
            entry["host"] = s["host"]
        if s.get("role") is not None:
            entry["role"] = s["role"]
        detail = {k: v for k, v in s.items()
                  if k not in _ENTRY_ATTR_SKIP
                  and k not in ("host", "role")}
        if detail:
            entry["detail"] = {k: detail[k] for k in sorted(detail)}
        entries.append(entry)
    entries.sort(key=lambda e: (e["ts"], e["name"], e.get("trace")
                                or ""))
    if limit is not None and limit >= 0:
        entries = entries[-limit:] if limit > 0 else []
    return entries


def render_timeline(entries: list[dict]) -> str:
    """Timeline entries -> NDJSON (one entry per line, key-sorted) --
    what ``?timeline=1`` serves and ``obs.tool timeline`` prints, so
    the two are byte-comparable."""
    import json

    if not entries:
        return ""
    return "\n".join(json.dumps(e, sort_keys=True)
                     for e in entries) + "\n"
