"""SLO tracking: per-kernel availability + latency objectives with
multi-window error-budget burn rates (ISSUE 10 tentpole, part 3).

An SLO here is the standard two-piece contract:

* **availability** -- at most ``1 - target`` of requests may fail with
  a server-caused error (HTTP >= 500: internal errors, mesh
  unavailability, deadline expiry).  Client-caused 4xx (bad input,
  over-quota 429) spends no budget.
* **latency** -- at most 1 % of completed requests may exceed the p99
  target (``--slo-p99-ms``); the budget is the 1 % by construction.

Each objective is tracked per kernel over TWO sliding windows -- a fast
one (default 300 s, ``HPNN_SLO_FAST_S``) and a slow one (default
3600 s, ``HPNN_SLO_SLOW_S``) -- as time-bucketed counters, so memory is
O(window / bucket) regardless of traffic and a burn-rate read is one
pass over ~256 buckets.  The *burn rate* is ``bad_fraction / budget``:
1.0 means the error budget is being spent exactly at the rate that
exhausts it over the SLO period, 14.4 (the classic fast-page threshold,
``HPNN_SLO_BURN``) means a 30-day budget dies in ~2 days.

**Alerting** follows the multi-window rule: an objective is *burning*
only when the fast AND slow windows both exceed the threshold -- the
fast window makes the alert responsive, the slow window keeps a brief
blip from paging.  On the transition into burning a structured
``nn_event("slo_burn", ...)`` fires (one JSON line under
``HPNN_LOG_JSON=1``); the event re-arms when the objective stops
burning, so a sustained incident emits one alert, not one per scrape.

Zero-cost when off: serving constructs no tracker unless an SLO knob is
set (``--slo-p99-ms`` / ``--slo-availability``), and every call site
guards on ``tracker is not None`` -- the off path is one attribute
read.

The burn signal is also an ACTUATOR input (ISSUE 13): the
transition-maintained ``burning_count`` / :meth:`SloTracker.any_burning`
is what ``serve.mesh.qos.LoadShedder`` polls per request to shed the
low QoS lane at admission while a budget burns -- one int read on the
healthy path, never a bucket scan.
"""

from __future__ import annotations

import threading
import time

from ..utils.env import env_float
from ..utils.nn_log import nn_event

# burn-rate threshold: both windows past it => burning (page-worthy)
_DEFAULT_BURN = 14.4
_DEFAULT_FAST_S = 300.0
_DEFAULT_SLOW_S = 3600.0


class _Window:
    """Time-bucketed (total, bad) counters covering the slow window;
    both burn rates read from one bucket map."""

    __slots__ = ("width", "keep", "buckets")

    def __init__(self, slow_s: float, fast_s: float,
                 resolution: int = 256):
        # bucket width: coarse enough that the slow window stays
        # ~resolution buckets, but ALWAYS fine enough that the FAST
        # window spans >= 8 buckets -- with e.g. a 24 h slow window and
        # a 300 s fast one, slow_s/256 alone would exceed the fast
        # window and its fraction would intermittently cover ZERO
        # buckets (burn flapping to 0 mid-incident)
        self.width = max(min(slow_s / resolution, fast_s / 8.0), 0.001)
        self.keep = int(slow_s / self.width) + 2
        self.buckets: dict[int, list] = {}  # idx -> [total, bad]

    def add(self, now: float, bad: bool) -> None:
        idx = int(now / self.width)
        acc = self.buckets.get(idx)
        if acc is None:
            acc = self.buckets[idx] = [0, 0]
            if len(self.buckets) > self.keep:  # prune past the slow win
                floor = idx - self.keep
                for k in [k for k in self.buckets if k < floor]:
                    del self.buckets[k]
        acc[0] += 1
        if bad:
            acc[1] += 1

    def fraction(self, now: float, window_s: float) -> tuple[float, int]:
        """(bad fraction, total) over the trailing ``window_s``."""
        floor = int((now - window_s) / self.width)
        total = bad = 0
        for idx, (t, b) in self.buckets.items():
            if idx > floor:
                total += t
                bad += b
        return (bad / total if total else 0.0), total


class _Objective:
    __slots__ = ("budget", "window", "burning", "kind", "last_eval")

    def __init__(self, kind: str, budget: float, slow_s: float,
                 fast_s: float):
        self.kind = kind
        self.budget = max(budget, 1e-9)
        self.window = _Window(slow_s, fast_s)
        self.burning = False
        self.last_eval = 0.0  # monotonic; throttles hot-path evals


class SloTracker:
    """Per-kernel availability/latency SLO state.  ``record_outcome``
    feeds the availability objective (every request, ok or not);
    ``record_latency`` feeds the latency objective (completed requests
    only -- the micro-batcher's honest whole-request wall)."""

    def __init__(self, availability: float | None = None,
                 p99_ms: float | None = None,
                 fast_s: float | None = None,
                 slow_s: float | None = None,
                 burn_threshold: float | None = None):
        self.availability = availability
        self.p99_ms = p99_ms
        self.fast_s = (fast_s if fast_s is not None
                       else env_float("HPNN_SLO_FAST_S", _DEFAULT_FAST_S))
        self.slow_s = (slow_s if slow_s is not None
                       else env_float("HPNN_SLO_SLOW_S", _DEFAULT_SLOW_S))
        self.slow_s = max(self.slow_s, self.fast_s)
        self.burn_threshold = (
            burn_threshold if burn_threshold is not None
            else env_float("HPNN_SLO_BURN", _DEFAULT_BURN))
        # hot-path evaluation throttle: a burn read scans the bucket
        # map, so records between ticks skip it -- alerts still fire no
        # later than the next tick or /metrics scrape (snapshot always
        # evaluates).  Scaled to the fast window so second-scale test
        # windows stay effectively per-record
        self.eval_interval_s = min(1.0, self.fast_s / 10.0)
        self._lock = threading.Lock()
        # (kernel, kind) -> _Objective, created on first record
        self._objectives: dict[tuple[str, str], _Objective] = {}
        self.alerts_total = 0
        # count of currently-burning objectives, maintained at the
        # burn/clear transitions: what an actuator (the load shedder)
        # polls per request -- one int read, no lock, no bucket scan
        self.burning_count = 0

    # objectives are per-kernel forever; a registry serves a handful of
    # kernels, so anything past this cap is junk input (defense in
    # depth behind the server's not-found exclusion) -- dropped, never
    # a memory / label-cardinality leak
    MAX_KERNELS = 128

    def _obj(self, kernel: str, kind: str,
             budget: float) -> _Objective | None:
        key = (kernel, kind)
        o = self._objectives.get(key)
        if o is None:
            if len(self._objectives) >= 2 * self.MAX_KERNELS:
                return None
            o = self._objectives[key] = _Objective(
                kind, budget, self.slow_s, self.fast_s)
        return o

    def record_outcome(self, kernel: str, ok: bool) -> None:
        """One request against the availability objective; ``ok`` is
        False only for server-caused failures (HTTP >= 500)."""
        if self.availability is None:
            return
        with self._lock:
            o = self._obj(kernel, "availability",
                          1.0 - self.availability)
            if o is None:
                return
            now = time.monotonic()
            o.window.add(now, not ok)
            self._maybe_evaluate_locked(kernel, o, now)

    def record_latency(self, kernel: str, seconds: float) -> None:
        """One completed request against the latency objective (bad
        when it exceeded the p99 target; the 1 % tail IS the budget)."""
        if self.p99_ms is None:
            return
        with self._lock:
            o = self._obj(kernel, "latency", 0.01)
            if o is None:
                return
            now = time.monotonic()
            o.window.add(now, seconds * 1e3 > self.p99_ms)
            self._maybe_evaluate_locked(kernel, o, now)

    # --- burn evaluation ------------------------------------------------
    def _burns_locked(self, o: _Objective,
                      now: float) -> tuple[float, float, int]:
        ffrac, _ = o.window.fraction(now, self.fast_s)
        sfrac, total = o.window.fraction(now, self.slow_s)
        return ffrac / o.budget, sfrac / o.budget, total

    def _maybe_evaluate_locked(self, kernel: str, o: _Objective,
                               now: float) -> None:
        """Throttled hot-path evaluation: the full bucket scan runs at
        most once per eval interval per objective."""
        if now - o.last_eval >= self.eval_interval_s:
            self._evaluate_locked(kernel, o)

    def _evaluate_locked(self, kernel: str, o: _Objective) -> None:
        o.last_eval = time.monotonic()
        fast, slow, total = self._burns_locked(o, o.last_eval)
        burning = (fast >= self.burn_threshold
                   and slow >= self.burn_threshold and total > 0)
        if burning and not o.burning:
            o.burning = True
            self.alerts_total += 1
            self.burning_count += 1
            # fire OUTSIDE the hot path's lock?  The event is one
            # formatted line; holding the lock keeps the transition
            # atomic (no double-fire from racing requests)
            nn_event("slo_burn", kernel=kernel, objective=o.kind,
                     fast_burn=round(fast, 2), slow_burn=round(slow, 2),
                     threshold=self.burn_threshold,
                     budget=o.budget)
        elif not burning and o.burning:
            o.burning = False
            self.burning_count = max(0, self.burning_count - 1)
            nn_event("slo_burn_cleared", kernel=kernel,
                     objective=o.kind, fast_burn=round(fast, 2),
                     slow_burn=round(slow, 2))

    def any_burning(self) -> bool:
        """True while at least one objective is burning -- the signal
        an actuator polls per request.  Deliberately reads the
        transition-maintained counter (one int read); freshness is
        bounded by the eval throttle + the /metrics scrape, both of
        which re-evaluate idle objectives."""
        return self.burning_count > 0

    def evaluate_now(self) -> bool:
        """Force a full re-evaluation of every objective (windows may
        have slid past the bad events with no new traffic to trigger
        the throttled hot-path eval).  Returns :meth:`any_burning`."""
        with self._lock:
            for (kernel, _kind), o in list(self._objectives.items()):
                self._evaluate_locked(kernel, o)
        return self.any_burning()

    # --- read side ------------------------------------------------------
    def snapshot(self) -> dict:
        """Per-kernel burn-rate gauges (what /metrics renders).
        Re-evaluates each objective, so an alert fires no later than
        the next scrape even on an idle kernel."""
        now = time.monotonic()
        out: dict = {
            "availability_target": self.availability,
            "p99_target_ms": self.p99_ms,
            "fast_window_s": self.fast_s,
            "slow_window_s": self.slow_s,
            "burn_threshold": self.burn_threshold,
            "kernels": {},
        }
        with self._lock:
            for (kernel, kind), o in sorted(self._objectives.items()):
                self._evaluate_locked(kernel, o)
                fast, slow, total = self._burns_locked(o, now)
                out["kernels"].setdefault(kernel, {})[kind] = {
                    "fast_burn": round(fast, 4),
                    "slow_burn": round(slow, 4),
                    "burning": o.burning,
                    "window_requests": total,
                    "budget": o.budget,
                }
            out["alerts_total"] = self.alerts_total
        return out
