"""Cross-host trace index over the durable span spool (ISSUE 15
tentpole, part 1).

PR 13's exporter made spans durable -- rotating NDJSON segments under
``--span-dir`` -- but the spool was write-only in practice: answering
"which traces were slow for kernel X?" meant re-parsing every segment
body.  This module gives each FINALIZED segment a sidecar index::

    spans-<unix>-<pid>-<seq>.ndjson          the segment (unchanged)
    spans-<unix>-<pid>-<seq>.ndjson.idx.json the sidecar

The sidecar maps ``trace id -> byte offsets`` of that trace's lines
inside the segment plus a per-trace summary (kernel, root span name,
status, start timestamp, wall extent, span count), so

* :func:`search` answers kernel/trace/min_ms/status/since/until
  queries from the sidecars alone -- segment BODIES are read only for
  traces the caller actually fetches;
* :func:`fetch_trace` seeks straight to a trace's lines instead of
  scanning the directory.

Index lifecycle:

* **built at rotation** -- the exporter calls :func:`build_index` on
  the writer thread right after a segment is finalized (indexing rides
  rotation, never the request path; ``HPNN_TRACE_INDEX=0`` disables);
* **lazily back-filled** -- :func:`ensure_index` builds the sidecar
  for a pre-existing / foreign segment the first time a query touches
  it, and REBUILDS it when it is stale (segment size mismatch -- a
  finalized segment never changes, so staleness means a torn or
  half-copied sidecar) or unreadable.  A failed sidecar write degrades
  to the in-memory scan result -- queries never fail because the
  directory is read-only;
* **open spools are always scanned** -- the in-progress ``.spool-*``
  files have no sidecar by construction (they are still growing).

Every summary field is derived deterministically from the span lines,
so the live endpoints and the offline tool (:mod:`.tool`) produce
byte-identical answers over the same directory.
"""

from __future__ import annotations

import json
import os

from ..utils.env import env_int

INDEX_SUFFIX = ".idx.json"
INDEX_VERSION = 1

_DEFAULT_SEARCH_LIMIT = 100


def index_enabled() -> bool:
    """``HPNN_TRACE_INDEX`` gate (default on): 0 disables rotation-time
    builds AND lazy back-fill -- every query scans segment bodies."""
    return os.environ.get("HPNN_TRACE_INDEX", "") != "0"


def search_limit_default() -> int:
    return env_int("HPNN_TRACE_SEARCH_LIMIT", _DEFAULT_SEARCH_LIMIT,
                   lo=1)


def index_path(segment_path: str) -> str:
    return segment_path + INDEX_SUFFIX


# --- per-segment summaries --------------------------------------------------

def _new_summary() -> dict:
    return {"offsets": [], "spans": 0, "kernel": None, "root": None,
            "status": None, "start_ts": None, "end_ts": None}


def _fold_span(summary: dict, span: dict, offset: int | None) -> None:
    """Fold one span line into its trace's summary (offset is None for
    in-memory spans, e.g. the flight-recorder ring)."""
    if offset is not None:
        summary["offsets"].append(offset)
    summary["spans"] += 1
    ts = span.get("ts")
    if isinstance(ts, (int, float)):
        if summary["start_ts"] is None or ts < summary["start_ts"]:
            summary["start_ts"] = ts
        end = ts + (span.get("dur_s") or 0.0)
        if summary["end_ts"] is None or end > summary["end_ts"]:
            summary["end_ts"] = end
    name = span.get("name") or ""
    if (summary["kernel"] is None and span.get("kernel")
            and not name.startswith(("event.", "mesh."))):
        # request/job/epoch spans name their kernel; a structured
        # EVENT mentioning one (slo_burn kernel=..., slow_request)
        # must not drag the whole events/mesh trace into that
        # kernel's search and critical-path results
        summary["kernel"] = str(span["kernel"])
    if span.get("parent") is None:
        # roots carry the trace's identity: the EARLIEST root (the
        # request/job/epoch that opened the trace) names it, the
        # NEWEST root with an outcome is its status -- a retried
        # request's final verdict wins
        rts = ts if isinstance(ts, (int, float)) else 0.0
        if summary["root"] is None or rts < summary["_root_ts"]:
            summary["root"] = span.get("name")
            summary["_root_ts"] = rts
        if span.get("outcome") is not None \
                and rts >= summary.get("_status_ts", -1.0):
            summary["status"] = str(span["outcome"])
            summary["_status_ts"] = rts


def _finish_summary(tid: str, summary: dict) -> dict:
    start = summary["start_ts"] or 0.0
    end = summary["end_ts"] or start
    out = {
        "trace": tid,
        "kernel": summary["kernel"],
        "root": summary["root"],
        "status": summary["status"],
        "start_ts": round(start, 6),
        "dur_ms": round((end - start) * 1e3, 3),
        "spans": summary["spans"],
    }
    if summary["offsets"]:
        out["offsets"] = summary["offsets"]
    return out


def summarize_spans(spans: list[dict]) -> list[dict]:
    """Per-trace summary rows for IN-MEMORY spans (the ring / fleet
    store path, and the open-spool scan) -- the same derivation the
    sidecar stores, minus byte offsets."""
    acc: dict[str, dict] = {}
    for s in spans:
        if not isinstance(s, dict):
            continue
        if s.get("name") == "trace.truncated":
            continue  # fleet-merger bookkeeping, not trace content
        tid = s.get("trace")
        if not tid:
            continue
        summary = acc.get(tid)
        if summary is None:
            summary = acc[tid] = _new_summary()
        _fold_span(summary, s, None)
    return [_finish_summary(tid, summ) for tid, summ in acc.items()]


def _scan_segment(path: str) -> dict[str, dict]:
    """Parse one NDJSON file tracking byte offsets; returns trace id ->
    raw summary.  Torn tails (a killed writer's half line) are skipped,
    like :func:`..export.read_spool`."""
    acc: dict[str, dict] = {}
    with open(path, "rb") as fp:
        offset = 0
        for raw in fp:
            line_off = offset
            offset += len(raw)
            line = raw.strip()
            if not line:
                continue
            try:
                s = json.loads(line.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # torn tail
            if not isinstance(s, dict):
                continue
            tid = s.get("trace")
            if not tid:
                continue
            summary = acc.get(tid)
            if summary is None:
                summary = acc[tid] = _new_summary()
            _fold_span(summary, s, line_off)
    return acc


def build_index(segment_path: str) -> dict | None:
    """Scan one finalized segment and write its sidecar (atomic
    tmp+fsync+rename).  Returns the index dict, or None when the
    segment is unreadable.  A failed sidecar WRITE still returns the
    in-memory index -- the caller's query proceeds, only the cache is
    lost (read-only span dirs stay queryable)."""
    try:
        st = os.stat(segment_path)
        acc = _scan_segment(segment_path)
    except OSError:
        return None
    idx = {
        "version": INDEX_VERSION,
        "segment": os.path.basename(segment_path),
        "size": st.st_size,
        "traces": {tid: _finish_summary(tid, summ)
                   for tid, summ in acc.items()},
    }
    for t in idx["traces"].values():
        t.pop("trace", None)  # keyed by trace id; no duplicate field
    try:
        from ..io.atomic import atomic_write_text

        atomic_write_text(index_path(segment_path),
                          json.dumps(idx, sort_keys=True) + "\n")
    except OSError:
        pass
    return idx


def load_index(segment_path: str) -> dict | None:
    """The sidecar, or None when missing / unreadable / wrong version /
    STALE (size mismatch vs the segment -- finalized segments never
    change, so a mismatch means the sidecar is the broken half)."""
    try:
        with open(index_path(segment_path), encoding="utf-8") as fp:
            idx = json.load(fp)
        seg_size = os.stat(segment_path).st_size
    except (OSError, json.JSONDecodeError):
        return None
    if (not isinstance(idx, dict)
            or idx.get("version") != INDEX_VERSION
            or idx.get("size") != seg_size
            or not isinstance(idx.get("traces"), dict)):
        return None
    return idx


def ensure_index(segment_path: str) -> dict | None:
    """Load-or-build: the lazy back-fill path queries go through.  A
    missing or stale sidecar falls back to a scan whose result REPAIRS
    the sidecar for the next query.  With ``HPNN_TRACE_INDEX=0`` the
    scan result is returned without writing anything."""
    idx = load_index(segment_path)
    if idx is not None:
        return idx
    if not index_enabled():
        try:
            acc = _scan_segment(segment_path)
        except OSError:
            return None
        return {"version": INDEX_VERSION,
                "segment": os.path.basename(segment_path),
                "traces": {tid: _finish_summary(tid, summ)
                           for tid, summ in acc.items()}}
    return build_index(segment_path)


# --- directory-level queries ------------------------------------------------

def _merge_row(into: dict, row: dict) -> None:
    """Fold one segment's summary of a trace into the cross-segment
    row (a trace routinely spans segments: its spans arrive over
    several rotations)."""
    into["spans"] += row.get("spans", 0)
    rs = row.get("start_ts")
    if rs is not None and (into["start_ts"] is None
                           or rs < into["start_ts"]):
        into["start_ts"] = rs
        if row.get("root") is not None:
            into["root"] = row["root"]
    elif into["root"] is None and row.get("root") is not None:
        into["root"] = row["root"]
    r_end = (row.get("start_ts") or 0.0) + (row.get("dur_ms")
                                            or 0.0) / 1e3
    if r_end > into["_end"]:
        into["_end"] = r_end
        if row.get("status") is not None:
            into["status"] = row["status"]
    elif into["status"] is None and row.get("status") is not None:
        into["status"] = row["status"]
    if into["kernel"] is None and row.get("kernel") is not None:
        into["kernel"] = row["kernel"]


def _dir_rows(span_dir: str) -> dict[str, dict]:
    """Every trace in the spool as a merged cross-segment row keyed by
    trace id: finalized segments through their sidecars (built/
    repaired as needed), open spools by scan."""
    from .export import list_segments

    rows: dict[str, dict] = {}

    def fold(tid: str, row: dict, segment: str | None) -> None:
        into = rows.get(tid)
        if into is None:
            into = rows[tid] = {
                "trace": tid, "kernel": None, "root": None,
                "status": None, "start_ts": None, "spans": 0,
                "_end": 0.0, "_segments": []}
        if into["start_ts"] is None:
            into["start_ts"] = row.get("start_ts")
        _merge_row(into, row)
        if segment is not None:
            into["_segments"].append(
                (segment, row.get("offsets") or None))

    finalized = list_segments(span_dir)
    for seg in finalized:
        idx = ensure_index(seg)
        if idx is None:
            continue
        for tid, row in idx["traces"].items():
            fold(tid, row, seg)
    for path in list_segments(span_dir, include_open=True):
        if path in finalized:
            continue
        try:
            acc = _scan_segment(path)
        except OSError:
            continue
        for tid, summ in acc.items():
            fold(tid, _finish_summary(tid, summ), path)
    return rows


def normalize_query(params: dict) -> dict:
    """Validated + normalized search parameters (shared by the live
    endpoint and the offline tool, so both echo the SAME query object
    and produce byte-identical payloads).  Raises ValueError on a
    malformed number."""
    q: dict = {}
    if params.get("kernel"):
        q["kernel"] = str(params["kernel"])
    if params.get("trace"):
        q["trace"] = str(params["trace"])
    if params.get("status"):
        q["status"] = str(params["status"])
    for key in ("min_ms", "since", "until"):
        if params.get(key) not in (None, ""):
            q[key] = float(params[key])
    if params.get("limit") not in (None, ""):
        q["limit"] = int(params["limit"])
    else:
        q["limit"] = search_limit_default()
    return q


def filter_rows(rows: list[dict], q: dict) -> list[dict]:
    """Apply a normalized query to summary rows: filters, then
    newest-first ordering, then the limit.  Deterministic tie-break on
    trace id so repeated queries over the same spool are byte-stable."""
    out = []
    for r in rows:
        if q.get("kernel") is not None and r.get("kernel") != q["kernel"]:
            continue
        if q.get("trace") is not None and r.get("trace") != q["trace"]:
            continue
        if q.get("status") is not None and r.get("status") != q["status"]:
            continue
        if q.get("min_ms") is not None \
                and (r.get("dur_ms") or 0.0) < q["min_ms"]:
            continue
        start = r.get("start_ts") or 0.0
        if q.get("since") is not None and start < q["since"]:
            continue
        if q.get("until") is not None and start > q["until"]:
            continue
        out.append(r)
    out.sort(key=lambda r: (-(r.get("start_ts") or 0.0),
                            r.get("trace") or ""))
    limit = q.get("limit")
    if limit is not None and limit >= 0:
        out = out[:limit]
    return out


def _public_row(row: dict) -> dict:
    # one canonical key order for every search source, so live and
    # offline payloads over the same spool are byte-identical
    return {"trace": row.get("trace"), "kernel": row.get("kernel"),
            "root": row.get("root"), "status": row.get("status"),
            "start_ts": row.get("start_ts"),
            "dur_ms": row.get("dur_ms"), "spans": row.get("spans")}


def search(span_dir: str, params: dict) -> dict:
    """The query payload ``GET /v1/debug/trace/search`` serves when a
    span spool is configured -- and EXACTLY what ``obs.tool search``
    prints offline.  ``params`` are raw string-ish values (query
    string / argv); see :func:`normalize_query` for the keys."""
    q = normalize_query(params)
    rows = []
    for r in _dir_rows(span_dir).values():
        start = r.get("start_ts") or 0.0
        r["dur_ms"] = round(max(r["_end"] - start, 0.0) * 1e3, 3)
        rows.append(r)
    rows = [_public_row(r) for r in filter_rows(rows, q)]
    return {"query": q, "count": len(rows), "traces": rows}


def search_spans(spans: list[dict], params: dict) -> dict:
    """The same search payload over IN-MEMORY spans (the ring / fleet
    store) -- what a server without a span spool answers from."""
    q = normalize_query(params)
    rows = [_public_row(r)
            for r in filter_rows(summarize_spans(spans), q)]
    return {"query": q, "count": len(rows), "traces": rows}


def fetch_trace(span_dir: str, trace_id: str) -> list[dict]:
    """Every spooled span of one trace, seeked through the sidecar
    offsets (segments without offsets -- or open spools -- are
    scanned), time-ordered like the merged dump."""
    return fetch_traces(span_dir, [trace_id]).get(trace_id, [])


def fetch_traces(span_dir: str,
                 trace_ids: list[str]) -> dict[str, list[dict]]:
    """Batch form of :func:`fetch_trace`: ONE directory pass (sidecars
    parsed / open spools scanned once) serves every requested trace --
    what the critical-path report fans out through."""
    rows = _dir_rows(span_dir)
    out: dict[str, list[dict]] = {}
    for trace_id in trace_ids:
        row = rows.get(trace_id)
        if row is None:
            continue
        spans: list[dict] = []
        for seg, offsets in row["_segments"]:
            try:
                if offsets:
                    with open(seg, "rb") as fp:
                        for off in offsets:
                            fp.seek(off)
                            line = fp.readline()
                            try:
                                s = json.loads(line.decode("utf-8"))
                            except (json.JSONDecodeError,
                                    UnicodeDecodeError):
                                continue
                            if isinstance(s, dict) \
                                    and s.get("trace") == trace_id:
                                spans.append(s)
                else:
                    for s in _iter_spans(seg):
                        if s.get("trace") == trace_id:
                            spans.append(s)
            except OSError:
                continue
        spans.sort(key=lambda s: (s.get("ts", 0.0), s.get("seq", 0)))
        out[trace_id] = spans
    return out


def _iter_spans(path: str):
    with open(path, "rb") as fp:
        for raw in fp:
            line = raw.strip()
            if not line:
                continue
            try:
                s = json.loads(line.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if isinstance(s, dict):
                yield s
