"""Span tracing + the in-process flight recorder.

A *span* is one timed region: ``{"name", "trace", "span", "parent",
"ts", "dur_s", "thread", ...attrs}``.  Spans belong to a *trace* (one
request, one training job, one CLI run); parentage makes the dump a
tree.  Completed spans land in a bounded ring buffer -- the flight
recorder -- oldest evicted first, so a long-lived server always holds
the most recent window of activity and a crash dump shows what the
process was doing right before the fault.

Design constraints (the serving p99 budget):

* **off = free.**  The global state is one module attribute; when it is
  ``None``, :func:`span` returns a shared no-op singleton and
  :func:`record` returns immediately -- no object allocation, no lock,
  no clock read.  The acceptance floor (serve_bench p99 regression
  < 5 % with tracing disabled) is held by this guard.
* **on = cheap.**  A span is one small object, two monotonic clock
  reads, and one deque append under a lock at completion.  Nothing is
  formatted until a dump is requested.
* **observe, never perturb.**  Recording never prints, never touches
  the device, and never raises into the traced code path (ring append
  failures are impossible by construction; attribute rendering happens
  at dump time inside the dump call).

Cross-thread correlation: the implicit parent is thread-local (nested
``with span(...)`` blocks form a stack), and code that hops threads --
the micro-batcher completing a request admitted by an HTTP thread --
passes ``trace_id``/``parent_id`` explicitly to :func:`record` with
measured start/end times.  Trace ids are caller-meaningful strings
(a request's ``X-HPNN-Trace-Id``, a job id); :func:`new_trace_id`
mints a random one when the caller has none.

Cross-HOST correlation (ISSUE 10): every recorded span carries a
monotone per-process ``seq`` number, so a remote collector (the mesh
router's fleet drain) can page the ring incrementally with
``since_seq=N`` instead of re-shipping the whole window every poll --
:func:`snapshot` filters on it and :func:`last_seq` is the cursor a
scraper stores.  ``seq`` restarts when the ring is re-enabled (or the
process restarts); collectors detect that by a ``last_seq`` smaller
than their cursor and rewind to 0.  :func:`set_role` names this
process's mesh role (router/worker/local): the SIGTERM/fault auto-dump
filename includes it (``trace-<reason>-<role>-<pid>.ndjson``) so a
killed fleet's post-mortems are attributable at a glance.

Head-based sampling (ISSUE 13): full capture cannot survive fleet QPS,
so the keep/drop decision is made ONCE at trace birth --
:func:`sample_trace` -- and everything under a dropped trace takes the
PR-8 zero-allocation no-op path (the HTTP layer simply never mints a
trace context).  ``HPNN_TRACE_SAMPLE=p`` / ``serve_nn --trace-sample``
set the probability; an explicit ``X-HPNN-Trace-Id`` or a high-QoS
request FORCES capture (``force=True``), so a debugging client or the
traffic you page on always records; ``HPNN_TRACE_SAMPLE_SEED`` makes
the coin deterministic for tests.  Sampled/dropped/forced counters are
exported in /metrics.  With no sampler configured every trace is kept
-- byte-identical to the pre-sampling behavior.

Durable export (ISSUE 13): :func:`set_exporter` attaches an
:class:`~.export.SpanExporter`; every completed span is then ALSO
offered to its bounded background spool (rotating NDJSON segment files
under ``--span-dir``), so post-hoc analysis survives SIGKILL of this
process -- and :func:`dump_to_dir` reuses that spool (one writer, not
two) whenever an exporter is active.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import uuid
from collections import deque

_DEFAULT_CAPACITY = 8192

# the whole on/off switch: a _State when tracing, None when off
_state: "_State | None" = None
_tls = threading.local()
# this process's mesh role ("router"/"worker"/"local"); None outside a
# serving context -- names the auto-dump file, never the hot path
_role: str | None = None
# head-based sampling: None = keep every trace (the pre-ISSUE-13
# behavior); a _Sampler when HPNN_TRACE_SAMPLE / --trace-sample set one
_sampler: "_Sampler | None" = None
# durable span spool: None = ring only; an export.SpanExporter when a
# --span-dir is configured (set_exporter)
_exporter = None


class _State:
    __slots__ = ("ring", "lock", "capacity", "wall_base", "mono_base",
                 "seq", "ring_id")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.ring: deque[dict] = deque(maxlen=self.capacity)
        self.lock = threading.Lock()
        self.seq = 0  # monotone span counter (the since_seq cursor)
        # ring identity: a fresh id per enable()/process, so a remote
        # collector can tell "this ring restarted" (cursor invalid)
        # apart from "entries were evicted" (cursor fine) -- seq alone
        # cannot distinguish a restart that already out-ran the cursor
        self.ring_id = uuid.uuid4().hex[:16]
        # one wall/monotonic anchor pair per enable(): span timestamps
        # are monotonic (elapsed math must survive clock steps) and the
        # dump renders them as wall time through this anchor
        self.wall_base = time.time()
        self.mono_base = time.monotonic()

    def wall(self, mono: float) -> float:
        return self.wall_base + (mono - self.mono_base)


def enabled() -> bool:
    return _state is not None


def enable(capacity: int | None = None) -> None:
    """Turn tracing on (idempotent; a repeat call with a different
    capacity re-rings, dropping recorded spans)."""
    global _state
    if capacity is None:
        from ..utils.env import env_int

        capacity = env_int("HPNN_TRACE_BUFFER", _DEFAULT_CAPACITY, lo=16)
    if _state is not None and _state.capacity == capacity:
        return
    _state = _State(capacity)


def disable() -> None:
    global _state
    _state = None


def enable_from_env() -> bool:
    """Enable when ``HPNN_TRACE`` is set truthy (the init_all / server
    startup hook); returns the resulting enabled state."""
    if os.environ.get("HPNN_TRACE", "") not in ("", "0"):
        enable()
    set_sample_rate_from_env()
    return enabled()


class _Sampler:
    """The head-sampling coin: one decision per trace at birth.  A
    dedicated ``random.Random`` (seedable via ``HPNN_TRACE_SAMPLE_SEED``
    for deterministic tests) so the decision stream is independent of
    every other RNG in the process; counters are the honest ledger of
    what the recorder did NOT see."""

    __slots__ = ("rate", "rng", "lock", "sampled_total",
                 "dropped_total", "forced_total")

    def __init__(self, rate: float, seed: int | None = None):
        self.rate = min(max(float(rate), 0.0), 1.0)
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        self.sampled_total = 0
        self.dropped_total = 0
        self.forced_total = 0

    def decide(self, force: bool = False) -> bool:
        with self.lock:
            if force:
                self.forced_total += 1
                self.sampled_total += 1
                return True
            if self.rng.random() < self.rate:
                self.sampled_total += 1
                return True
            self.dropped_total += 1
            return False


def set_sample_rate(rate: float | None,
                    seed: int | None = None) -> None:
    """Configure head sampling: traces are kept with probability
    ``rate`` (forced captures always win).  ``None`` (or a rate >= 1
    with no seed) removes the sampler -- every trace is kept and the
    counters disappear from /metrics."""
    global _sampler
    if rate is None:
        _sampler = None
        return
    if seed is None:
        env_seed = os.environ.get("HPNN_TRACE_SAMPLE_SEED", "")
        if env_seed:
            try:
                seed = int(env_seed)
            except ValueError:
                seed = None
    _sampler = _Sampler(rate, seed=seed)


def set_sample_rate_from_env() -> None:
    """Install a sampler when ``HPNN_TRACE_SAMPLE`` is set (idempotent
    no-op otherwise) -- the init_all / server-startup hook."""
    raw = os.environ.get("HPNN_TRACE_SAMPLE", "")
    if not raw:
        return
    try:
        set_sample_rate(float(raw))
    except ValueError:
        pass  # a malformed rate must not kill startup; keep-all default


def sample_trace(force: bool = False) -> bool:
    """The birth decision: should this trace be captured?  ``force``
    (an explicit ``X-HPNN-Trace-Id``, a high-QoS request) always keeps
    and is counted separately.  Without a sampler every trace is kept
    -- no lock, no counter, the pre-sampling fast path."""
    s = _sampler
    if s is None:
        return True
    return s.decide(force)


def sample_stats() -> dict | None:
    """Sampling counters for /metrics (None when no sampler is
    configured -- the series must not exist for a keep-all recorder)."""
    s = _sampler
    if s is None:
        return None
    with s.lock:
        return {"rate": s.rate, "sampled_total": s.sampled_total,
                "dropped_total": s.dropped_total,
                "forced_total": s.forced_total}


def set_exporter(exporter) -> None:
    """Attach (or, with None, detach) the durable span spool: every
    span recorded from here on is ALSO offered to
    ``exporter.offer(span)`` (an :class:`~.export.SpanExporter`)."""
    global _exporter
    _exporter = exporter


def get_exporter():
    return _exporter


def set_role(role: str | None) -> None:
    """Name this process's mesh role (router/worker/local) for the
    auto-dump filename; None restores the role-less legacy name."""
    global _role
    _role = role


def get_role() -> str | None:
    return _role


def last_seq() -> int:
    """The newest recorded span's ``seq`` (0 when tracing is off or
    nothing recorded) -- what ``X-HPNN-Trace-Seq`` reports so scrapers
    can page with ``since_seq`` and detect ring restarts."""
    st = _state
    return st.seq if st is not None else 0


def ring_id() -> str:
    """This ring's identity (fresh per enable()/process; "" when
    tracing is off) -- ``X-HPNN-Trace-Ring`` carries it so a collector
    invalidates its cursor on ANY restart, even one whose new seq
    already passed the old cursor."""
    st = _state
    return st.ring_id if st is not None else ""


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_ctx() -> tuple[str, str] | None:
    """The innermost active span's ``(trace_id, span_id)`` on this
    thread, or None -- what cross-thread code captures to parent its
    explicit :func:`record` calls."""
    st = getattr(_tls, "stack", None)
    if not st:
        return None
    top = st[-1]
    return (top.trace_id, top.span_id)


class _NoopSpan:
    """Shared do-nothing span: what :func:`span` hands out while
    tracing is off.  One module-level instance, so the disabled path
    allocates NOTHING (asserted in tests/test_obs.py)."""

    __slots__ = ()
    trace_id = ""
    span_id = ""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "_t0", "_st")

    def __init__(self, st: _State, name: str, trace_id: str | None,
                 parent_id: str | None, attrs: dict | None):
        self.name = name
        self.span_id = uuid.uuid4().hex[:16]
        self.attrs = attrs
        self._st = st
        self._t0 = 0.0
        if trace_id is None:
            ctx = current_ctx()
            if ctx is not None:
                trace_id, parent_id = ctx[0], ctx[1]
            else:
                trace_id = new_trace_id()
        self.trace_id = trace_id
        self.parent_id = parent_id

    def annotate(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._t0 = time.monotonic()
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.monotonic()
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        if exc_type is not None:
            self.annotate(error=f"{exc_type.__name__}: {exc}")
        _append(self._st, self.name, self.trace_id, self.span_id,
                self.parent_id, self._t0, t1, self.attrs)
        return False


def span(name: str, trace_id: str | None = None,
         parent_id: str | None = None, **attrs):
    """Context manager timing one region.  With tracing off this is the
    shared no-op singleton; on, the span nests under this thread's
    innermost active span unless ``trace_id``/``parent_id`` pin it
    explicitly."""
    st = _state
    if st is None:
        return _NOOP
    return Span(st, name, trace_id, parent_id, attrs or None)


def _append(st: _State, name: str, trace_id: str, span_id: str,
            parent_id: str | None, t0: float, t1: float,
            attrs: dict | None) -> None:
    rec = {
        "name": name,
        "trace": trace_id,
        "span": span_id,
        "parent": parent_id,
        "ts": round(st.wall(t0), 6),
        "dur_s": round(t1 - t0, 9),
        "thread": threading.current_thread().name,
    }
    if attrs:
        rec.update(attrs)
    with st.lock:
        st.seq += 1
        rec["seq"] = st.seq
        st.ring.append(rec)
    exp = _exporter
    if exp is not None:
        # the spool's bounded queue never blocks the traced path: a
        # full queue drops (counted), the ring is unaffected
        exp.offer(rec)


def record(name: str, t0: float, t1: float,
           trace_id: str | None = None, parent_id: str | None = None,
           span_id: str | None = None, **attrs) -> str:
    """Record a completed span from measured ``time.monotonic()``
    endpoints -- the cross-thread form (the batcher timing a batch
    segment for each member request).  ``span_id`` lets a caller
    pre-mint the id (the HTTP handler hands its root span's id to the
    batcher BEFORE the root completes).  Returns the span id ("" when
    tracing is off)."""
    st = _state
    if st is None:
        return ""
    if trace_id is None:
        ctx = current_ctx()
        if ctx is not None:
            trace_id, parent_id = ctx[0], ctx[1]
        else:
            trace_id = new_trace_id()
    if span_id is None:
        span_id = uuid.uuid4().hex[:16]
    _append(st, name, trace_id, span_id, parent_id, t0, t1,
            attrs or None)
    return span_id


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def snapshot(trace_id: str | None = None,
             limit: int | None = None,
             since_seq: int | None = None) -> list[dict]:
    """Recorded spans, oldest first; ``trace_id`` filters to one trace,
    ``since_seq`` keeps spans recorded after that cursor (incremental
    paging), ``limit`` keeps the newest N."""
    st = _state
    if st is None:
        return []
    with st.lock:
        spans = list(st.ring)
    if since_seq is not None and since_seq > 0:
        spans = [s for s in spans if s.get("seq", 0) > since_seq]
    if trace_id is not None:
        spans = [s for s in spans if s["trace"] == trace_id]
    if limit is not None:
        # limit <= 0 means "at most nothing" -- spans[-0:] would be the
        # WHOLE list, not an empty one
        spans = spans[-limit:] if limit > 0 else []
    return spans


def dump_ndjson(trace_id: str | None = None,
                limit: int | None = None,
                since_seq: int | None = None) -> str:
    """The flight-recorder dump: one JSON object per line (NDJSON),
    oldest span first -- what ``GET /v1/debug/trace`` serves."""
    return render_ndjson(snapshot(trace_id=trace_id, limit=limit,
                                  since_seq=since_seq))


def render_ndjson(spans: list[dict]) -> str:
    """Span dicts -> NDJSON text (the same line format dump_ndjson
    emits) -- what fleet-merged dumps render through."""
    if not spans:
        return ""
    return "\n".join(json.dumps(s, sort_keys=True) for s in spans) + "\n"


def dump_to_dir(dirpath: str, reason: str = "dump",
                extra_spans: list[dict] | None = None) -> str | None:
    """Write the recorder to ``<dirpath>/trace-<reason>[-<role>]-<pid>
    .ndjson`` (the SIGTERM/fault auto-dump; the role lands in the name
    once :func:`set_role` ran, so a killed fleet's dumps don't all look
    alike).  ``extra_spans`` ride along time-sorted into the same file
    -- a mesh router passes its last collected worker spans so remote
    halves of in-flight traces survive the process.  Best-effort:
    returns the path, or None when tracing is off / nothing is recorded
    / the write fails -- a dying process must not die harder because
    its post-mortem failed.

    With a durable exporter attached (``--span-dir``), the dump REUSES
    the spool instead of writing a second ad-hoc file (ISSUE 13
    satellite): the ring's spans are already streaming there, so the
    post-mortem is one flush + rotate -- extra spans ride into the
    same segment, and the returned path is the rotated segment."""
    exp = _exporter
    if exp is not None:
        try:
            if extra_spans:
                for s in extra_spans:
                    exp.offer(s)
            return exp.flush(reason=reason)
        except Exception:
            return None
    spans = snapshot()
    if extra_spans:
        spans = sorted(spans + list(extra_spans),
                       key=lambda s: s.get("ts", 0.0))
    text = render_ndjson(spans)
    if not text:
        return None
    role = f"-{_role}" if _role else ""
    path = os.path.join(dirpath,
                        f"trace-{reason}{role}-{os.getpid()}.ndjson")
    try:
        os.makedirs(dirpath, exist_ok=True)
        with open(path, "w") as fp:
            fp.write(text)
    except OSError:
        return None
    return path
