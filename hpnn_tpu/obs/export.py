"""Durable span export: a bounded background spool shipping completed
spans off the flight-recorder ring into rotating NDJSON segment files
(ISSUE 13 tentpole, part 2).

The PR-8 ring answers "what was this process doing right before now?",
but it dies with the process and evicts under load.  The exporter makes
the answer DURABLE without touching the traced hot path:

* **bounded offer** -- :meth:`SpanExporter.offer` appends to a bounded
  in-memory queue and returns; a full queue drops the span (counted in
  ``dropped_total`` -- an honest ledger, never backpressure into the
  serving path).  ``obs.trace._append`` offers every recorded span, so
  whatever head sampling kept is what the spool holds.
* **incremental segment writes** -- a daemon writer drains the queue
  into the current OPEN segment (``.spool-<pid>.open`` inside
  ``span_dir``), one JSON object per line, flushed per batch: after a
  SIGKILL the flushed lines are already with the OS, so the segment
  survives the process (fsync happens at rotation, so a power cut may
  cost the open segment's tail -- the same honesty gradient as the
  checkpoint writers).
* **rotation** -- when the open segment passes the size cap
  (``HPNN_SPAN_SEGMENT_KB``) or age cap (``HPNN_SPAN_SEGMENT_AGE_S``)
  it is fsync'd and atomically renamed to
  ``spans-<unix>-<pid>-<seq>.ndjson`` (the :mod:`..io.atomic`
  tmp+fsync+rename sequence -- the open file IS the temp file), and the
  parent directory is fsync'd, so finalized segments are durable
  through power loss.
* **retention** -- after every rotation, finalized segments beyond
  ``HPNN_SPAN_DIR_MAX_MB`` total (or older than ``HPNN_SPAN_KEEP_S``,
  when set) are deleted oldest-first; the sweep counts what it removed
  (``segments_pruned_total``) -- bounded disk, never a silent grow.

:func:`read_spool` is the read side: every finalized segment plus the
open spools, oldest first -- what ``GET /v1/debug/trace?spool=1``
serves and what a post-mortem reads after the process is gone.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from ..io.atomic import fsync_dir
from ..utils.env import env_float, env_int

_DEFAULT_SEGMENT_KB = 512
_DEFAULT_SEGMENT_AGE_S = 30.0
_DEFAULT_DIR_MAX_MB = 64
_DEFAULT_QUEUE_SPANS = 8192

SEGMENT_PREFIX = "spans-"
OPEN_PREFIX = ".spool-"


class SpanExporter:
    """See the module doc.  One instance per process (attached via
    ``obs.trace.set_exporter``); every method is thread-safe."""

    def __init__(self, span_dir: str,
                 segment_bytes: int | None = None,
                 segment_age_s: float | None = None,
                 max_dir_bytes: int | None = None,
                 keep_s: float | None = None,
                 queue_spans: int | None = None):
        self.span_dir = os.path.abspath(span_dir)
        os.makedirs(self.span_dir, exist_ok=True)
        self.segment_bytes = (
            segment_bytes if segment_bytes is not None
            else env_int("HPNN_SPAN_SEGMENT_KB", _DEFAULT_SEGMENT_KB,
                         lo=1) * 1024)
        self.segment_age_s = (
            segment_age_s if segment_age_s is not None
            else env_float("HPNN_SPAN_SEGMENT_AGE_S",
                           _DEFAULT_SEGMENT_AGE_S, lo=0.05))
        self.max_dir_bytes = (
            max_dir_bytes if max_dir_bytes is not None
            else env_int("HPNN_SPAN_DIR_MAX_MB", _DEFAULT_DIR_MAX_MB,
                         lo=1) * (1 << 20))
        self.keep_s = (keep_s if keep_s is not None
                       else env_float("HPNN_SPAN_KEEP_S", 0.0, lo=0.0))
        cap = (queue_spans if queue_spans is not None
               else env_int("HPNN_SPAN_QUEUE", _DEFAULT_QUEUE_SPANS,
                            lo=64))
        self._q: deque = deque()
        self._q_cap = int(cap)
        self._cv = threading.Condition()
        # serializes segment file IO (writer thread vs flush vs close)
        self._io = threading.Lock()
        self._open_path = os.path.join(
            self.span_dir, f"{OPEN_PREFIX}{os.getpid()}.open")
        self._fp = None
        self._open_bytes = 0
        self._open_since = time.monotonic()
        self._seq = 0
        self.exported_total = 0
        self.dropped_total = 0
        self.rotations_total = 0
        self.segments_pruned_total = 0
        # trace-index sidecars (ISSUE 15): built right after a segment
        # finalizes, ON the writer thread -- indexing rides rotation,
        # never the request path (HPNN_TRACE_INDEX=0 opts out; queries
        # then fall back to scans)
        from .index import index_enabled

        self.index_segments = index_enabled()
        self.index_builds_total = 0
        self.index_build_s_total = 0.0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="hpnn-span-exporter", daemon=True)
        self._thread.start()

    # --- producer side ---------------------------------------------------
    def offer(self, span: dict) -> bool:
        """Enqueue one completed span (non-blocking); False + counted
        when the bounded queue is full."""
        with self._cv:
            if self._closed or len(self._q) >= self._q_cap:
                self.dropped_total += 1
                return False
            self._q.append(span)
            self._cv.notify()
        return True

    # --- writer ----------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cv:
                if not self._q and not self._closed:
                    # bounded wait: the age-based rotation must fire
                    # even when nothing new arrives
                    self._cv.wait(timeout=min(0.5, self.segment_age_s))
                batch = list(self._q)
                self._q.clear()
                closed = self._closed
            with self._io:
                if batch:
                    self._write_locked(batch)
                self._maybe_rotate_locked()
            if closed:
                return

    def _ensure_open_locked(self):
        if self._fp is None:
            self._fp = open(self._open_path, "a", encoding="utf-8")
            self._open_bytes = self._fp.tell()
            self._open_since = time.monotonic()
        return self._fp

    def _write_locked(self, batch: list[dict]) -> None:
        for s in batch:
            try:
                line = json.dumps(s, sort_keys=True) + "\n"
            except (TypeError, ValueError):
                self.dropped_total += 1  # unserializable attr: drop it
                continue
            fp = self._ensure_open_locked()
            fp.write(line)
            self._open_bytes += len(line.encode("utf-8"))
            self.exported_total += 1
            if self._open_bytes >= self.segment_bytes:
                # rotate INSIDE a large drain, or one busy batch would
                # blow arbitrarily far past the segment cap
                self._maybe_rotate_locked()
        if self._fp is not None:
            # flush per batch: the bytes are with the OS, so a
            # SIGKILL'd process's spool is readable (fsync waits for
            # rotation)
            self._fp.flush()

    def _maybe_rotate_locked(self, force: bool = False) -> str | None:
        if self._fp is None or self._open_bytes == 0:
            return None
        age = time.monotonic() - self._open_since
        if not (force or self._open_bytes >= self.segment_bytes
                or age >= self.segment_age_s):
            return None
        fp = self._fp
        fp.flush()
        os.fsync(fp.fileno())
        fp.close()
        self._fp = None
        self._seq += 1
        # int(time.time()): the persisted segment timestamp in the name
        final = os.path.join(
            self.span_dir,
            f"{SEGMENT_PREFIX}{int(time.time())}-{os.getpid()}"
            f"-{self._seq:06d}.ndjson")
        try:
            os.replace(self._open_path, final)
        except OSError:
            return None
        fsync_dir(self.span_dir)
        self._open_bytes = 0
        self.rotations_total += 1
        self._retain_locked()
        if self.index_segments:
            # sidecar build rides the rotation (writer thread): search
            # never pays a back-fill for segments this process wrote
            t0 = time.monotonic()
            try:
                from .index import build_index

                build_index(final)
                self.index_builds_total += 1
                self.index_build_s_total += time.monotonic() - t0
            except Exception:
                pass  # queries fall back to the lazy scan-and-repair
        return final

    def _retain_locked(self) -> None:
        """Oldest-first prune of FINALIZED segments past the size/age
        caps (the open spools are never touched)."""
        try:
            names = sorted(n for n in os.listdir(self.span_dir)
                           if n.startswith(SEGMENT_PREFIX)
                           and n.endswith(".ndjson"))
        except OSError:
            return
        entries = []
        for n in names:
            p = os.path.join(self.span_dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, p, st.st_size))
        entries.sort()
        total = sum(sz for _, _, sz in entries)
        now = time.time()  # vs persisted segment mtimes ("updated")
        for mtime, path, sz in entries[:-1]:  # keep the newest always
            too_big = total > self.max_dir_bytes
            too_old = self.keep_s > 0 and now - mtime > self.keep_s
            if not (too_big or too_old):
                continue
            try:
                os.unlink(path)
            except OSError:
                continue
            try:  # the sidecar index dies with its segment
                from .index import index_path

                os.unlink(index_path(path))
            except OSError:
                pass
            total -= sz
            self.segments_pruned_total += 1

    # --- control ---------------------------------------------------------
    def drain(self) -> None:
        """Make every offered span readable NOW (write + flush the
        open segment) WITHOUT forcing a rotation -- the ``?spool=1``
        read path.  ``read_spool`` already includes open spools, so a
        polling dashboard must not turn every query into an fsync +
        rename + retention sweep."""
        with self._cv:
            batch = list(self._q)
            self._q.clear()
        with self._io:
            if batch:
                self._write_locked(batch)

    def flush(self, reason: str = "flush") -> str | None:
        """Drain the queue and force-rotate the open segment; returns
        the finalized segment's path (None when nothing was spooled).
        This is the SIGTERM/fault auto-dump: the spool already holds
        the ring's history, so a post-mortem is one rotation."""
        with self._cv:
            batch = list(self._q)
            self._q.clear()
        with self._io:
            if batch:
                self._write_locked(batch)
            path = self._maybe_rotate_locked(force=True)
        if path is None:
            # nothing pending: the newest finalized segment IS the
            # post-mortem (everything was already rotated out)
            segs = list_segments(self.span_dir)
            path = segs[-1] if segs else None
        return path

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
        with self._io:
            self._maybe_rotate_locked(force=True)
            if self._fp is not None:  # pragma: no cover - empty spool
                self._fp.close()
                self._fp = None

    def stats(self) -> dict:
        with self._io:
            open_bytes = self._open_bytes
        segs = list_segments(self.span_dir)
        oldest_age = 0.0
        if segs:
            try:
                mtime = os.stat(segs[0]).st_mtime
                oldest_age = max(0.0, time.time() - mtime)  # "updated"
            except OSError:
                pass
        return {"span_dir": self.span_dir,
                "exported_total": self.exported_total,
                "dropped_total": self.dropped_total,
                "rotations_total": self.rotations_total,
                "segments_pruned_total": self.segments_pruned_total,
                "segments": len(segs),
                "open_bytes": open_bytes,
                "oldest_segment_age_s": round(oldest_age, 3),
                "index_builds_total": self.index_builds_total,
                "index_build_s_total": round(self.index_build_s_total,
                                             6),
                "queue_depth": len(self._q)}


# --- read side -------------------------------------------------------------

def list_segments(span_dir: str, include_open: bool = False) -> list[str]:
    """Finalized segment paths oldest first (by name: the unix stamp +
    seq sort lexically); ``include_open`` appends in-progress spools."""
    try:
        names = os.listdir(span_dir)
    except OSError:
        return []
    segs = sorted(os.path.join(span_dir, n) for n in names
                  if n.startswith(SEGMENT_PREFIX)
                  and n.endswith(".ndjson"))
    if include_open:
        segs += sorted(os.path.join(span_dir, n) for n in names
                       if n.startswith(OPEN_PREFIX)
                       and n.endswith(".open"))
    return segs


def read_spool(span_dir: str, trace_id: str | None = None,
               limit: int | None = None) -> list[dict]:
    """Every span in the spool (finalized segments + open spools),
    oldest segment first; ``trace_id`` filters, ``limit`` keeps the
    newest N.  Tolerant of torn tails: a half-written last line (the
    process died mid-write) is skipped, everything before it is
    served."""
    spans: list[dict] = []
    for path in list_segments(span_dir, include_open=True):
        try:
            with open(path, encoding="utf-8") as fp:
                for line in fp:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        s = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail of a killed writer
                    if isinstance(s, dict):
                        spans.append(s)
        except OSError:
            continue
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace") == trace_id]
    if limit is not None:
        spans = spans[-limit:] if limit > 0 else []
    return spans
