"""pmnist: MNIST idx-ubyte files -> one text sample file per image.

Rebuild of ``/root/reference/tutorials/mnist/prepare_mnist.c``:

* reads ``./train_labels``, ``./train_images``, ``./test_labels``,
  ``./test_images`` (the renamed MNIST idx files, ``prepare_mnist.c:33-37``)
  from the current directory;
* writes ``s%05d.txt`` files -- the index CONTINUES from the training set
  into the test set (``prepare_mnist.c:73`` shares ``index``), so tests are
  s60001... on the standard corpus;
* sample format (``write_output``, ``prepare_mnist.c:47-60``):

      [input] 784
      <784 pixels at %7.5f, raw 0-255, NOT normalized>
      [output] 10  #<label>
      <one-hot as 1.0 / -1.0>

Reference bug handled: the test-set loop reads the first label TWICE
(``prepare_mnist.c:228-231`` duplicates the "first label" read), pairing
every test image i with label i+1 and dropping the last image -- the
reference's whole test corpus is mislabeled by one.  Default behavior here
is the CORRECT pairing; pass ``--reference-quirks`` to reproduce the
reference byte-for-byte (documented deviation).
"""

from __future__ import annotations

import os
import struct
import sys


def _read_idx_labels(path: str) -> tuple[int, list[int]]:
    try:
        fp = open(path, "rb")
    except OSError:
        sys.stderr.write(f"FAILED to open label file {path} for READ!\n")
        raise SystemExit(-1)
    with fp:
        try:
            magic, size = struct.unpack(">II", fp.read(8))
        except struct.error:
            sys.stderr.write(f"READ FAIL: {path}\n")
            raise SystemExit(-1)
        data = fp.read(size)
    return magic, list(data)


def _read_idx_images(path: str) -> tuple[int, list[bytes], int]:
    try:
        fp = open(path, "rb")
    except OSError:
        sys.stderr.write(f"FAILED to open image file {path} for READ!\n")
        raise SystemExit(-1)
    with fp:
        try:
            magic, size, rows, cols = struct.unpack(">IIII", fp.read(16))
        except struct.error:
            sys.stderr.write(f"READ FAIL: {path}\n")
            raise SystemExit(-1)
        npx = rows * cols
        images = []
        for i in range(size):
            img = fp.read(npx)
            if len(img) != npx:
                # short fread: the reference's _READ_N aborts
                # (prepare_mnist.c:130-136)
                sys.stderr.write(
                    f"READ FAIL: image {i + 1} read {len(img)} of "
                    f"{npx} requested\n")
                raise SystemExit(-1)
            images.append(img)
    return magic, images, npx


def write_sample(fp, pixels, label: int, n_out: int = 10) -> None:
    """write_output (prepare_mnist.c:47-60), byte-identical."""
    fp.write(f"[input] {len(pixels)}\n")
    fp.write(" ".join(f"{float(p):7.5f}" for p in pixels))
    fp.write("\n")
    fp.write(f"[output] {n_out}  #{label}\n")  # two spaces before #
    fp.write(" ".join("1.0" if label == i else "-1.0" for i in range(n_out)))
    fp.write("\n")


def convert_set(label_path: str, image_path: str, out_dir: str,
                start_index: int, what: str,
                quirk_offbyone: bool = False) -> int:
    """Convert one (labels, images) pair; returns the next free index."""
    magic_l, labels = _read_idx_labels(label_path)
    magic_i, images, npx = _read_idx_images(image_path)
    if len(labels) != len(images):
        sys.stderr.write(
            f"ERROR: different set size!\n-- {label_path} has "
            f"{len(labels)} and {image_path} has {len(images)}")
        raise SystemExit(-1)
    sys.stdout.write(f"# Opened {what} label={magic_l:X} image={magic_i:X}\n")
    if quirk_offbyone and what == "tests":
        # the reference consumes the first test label twice
        # (prepare_mnist.c:228-231): image i pairs with label i+1 and the
        # last image is dropped
        labels = labels[1:]
        images = images[: len(labels)]
    index = start_index
    for label, img in zip(labels, images):
        index += 1
        if label > 9:
            sys.stderr.write("ERROR: label out of boundaries!\n")
            continue
        name = os.path.join(out_dir, f"s{index:05d}.txt")
        try:
            fp = open(name, "w")
        except OSError:
            sys.stderr.write(f"FAILED to open sample {name} for WRITE!\n")
            raise SystemExit(-1)
        with fp:
            write_sample(fp, img, label)
    return index


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    quirk = "--reference-quirks" in argv
    argv = [a for a in argv if a != "--reference-quirks"]
    if argv and argv[0] in ("-h", "--h", "--help"):
        sys.stdout.write(
            "usage: pmnist [--reference-quirks] samples_dir tests_dir\n"
            "reads ./train_labels ./train_images ./test_labels "
            "./test_images (renamed MNIST idx files)\n")
        return 0
    if len(argv) < 2:
        sys.stderr.write("ERROR not enough arguments!\n")
        return 1
    sample_wd, test_wd = argv[0], argv[1]
    sys.stdout.write(
        f"processing sample database into {sample_wd} directory.\n")
    sys.stdout.write(
        f"processing   test database into {test_wd} directory.\n")
    idx = convert_set("./train_labels", "./train_images", sample_wd, 0,
                      "samples", quirk)
    if not quirk:
        # loud one-liner: the default test-set pairing FIXES the
        # reference's off-by-one label bug, so files will not be
        # byte-identical to a reference-generated corpus (ADVICE r1)
        sys.stdout.write(
            "note: test-set labels use the CORRECTED pairing; pass "
            "--reference-quirks to reproduce the reference's off-by-one "
            "byte-exactly.\n")
    convert_set("./test_labels", "./test_images", test_wd, idx,
                "tests", quirk)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
