"""pdif: RRUFF DIF + XY raw files -> XRD classification samples.

Rebuild of ``/root/reference/tutorials/ann/{prepare_dif.c,file_dif.c}``:
walks ``<rruff_dir>/dif/``, pairs each DIF file with the same-named file in
``<rruff_dir>/raw/``, and writes one sample per mineral into the sample
directory:

    [input] <n_in>                      (n_in = -i value + 1: temperature
    T/273.15 b1 ... b850  (%7.5f)        slot + XRD bins, prepare_dif.c:118)
    [output] 230
    one-hot 1.0/-1.0 at space_group-1   (all -1.0 when the group is unknown)

Bins integrate the raw XY intensities over [5, 90) degrees 2-theta in
``(90-5)/n_bins`` steps and are normalized to max 1.0
(``file_dif.c:425-465``; MIN/MAX_THETA ``file_dif.h:26-27``).

DIF parsing mirrors ``read_dif`` (``file_dif.c:37-330``): structure name on
line 1 (files R060187 / "5.000" rejected), sample temperature ``T = x K``
(Celsius assumed otherwise), CELL PARAMETERS (6 floats, mandatory), SPACE
GROUP by exact Hermann-Mauguin symbol lookup (sg_table), WAVELENGTH, and
the 2-THETA peak table (file invalid without peaks).  Files measured at the
Mo wavelength 0.710730 are skipped (``prepare_dif.c:226``).  Atom tables
are consumed but not used by the sample writer, as in the reference.
"""

from __future__ import annotations

import os
import re
import sys

from .sg_table import SPACE_GROUPS

MIN_THETA = 5.0   # file_dif.h:26
MAX_THETA = 90.0  # file_dif.h:27

_NUM = re.compile(r"[-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?")


class Dif:
    def __init__(self):
        self.name = "???"
        self.temp = 273.15 + 25.0  # room temperature (file_dif.c:87)
        self.space = 0             # 0 -> unknown (file_dif.c:88)
        self.lam = 1.541838        # file_dif.c:91
        self.n_peaks = 0
        self.raw_t: list[float] = []
        self.raw_i: list[float] = []


def _floats(text: str, n: int | None = None):
    vals = [float(m.group(0)) for m in _NUM.finditer(text)]
    if n is not None and len(vals) < n:
        return None
    return vals[:n] if n is not None else vals


def read_dif(path: str) -> Dif | None:
    """Parse a RRUFF DIF file (read_dif, file_dif.c:37-330)."""
    try:
        fp = open(path, "r", errors="replace")
    except OSError:
        sys.stderr.write(f"Error opening file: {path}\n")
        return None
    with fp:
        lines = fp.read().splitlines()
    if not lines:
        return None
    first = lines[0]
    # 4 structures lack full set information (file_dif.c:62-65)
    if "R060187" in first or "5.000" in first:
        return None
    dif = Dif()
    name = first.strip().split()
    dif.name = name[0] if name else "???"
    i = 1
    n = len(lines)
    while i < n:
        line = lines[i]
        if "Sample" in line and "T =" in line:
            after = line.split("T =", 1)[1]
            m = _NUM.search(after)
            if m:
                dif.temp = float(m.group(0))
                # unit is the char one past the number (file_dif.c:103-113):
                # 'K' keeps kelvin, anything else means Celsius
                tail = after[m.end():]
                if not (len(tail) >= 2 and tail[1] == "K"):
                    dif.temp += 273.15
        if "CELL PARAMETERS:" in line:
            vals = _floats(line.split("CELL PARAMETERS:", 1)[1], 6)
            if vals is None:
                return None  # mandatory (file_dif.c:121-132)
        if "SPACE GROUP" in line:
            # ptr+=11; skip optional '#'; +2 -> symbol start
            # (file_dif.c:135-140, incl. the R060879 "SPACE GROUP #:" case)
            rest = line.split("SPACE GROUP", 1)[1]
            if rest.startswith("#"):
                rest = rest[1:]
            sym = rest[2:].split()[0] if rest[2:].split() else ""
            if sym in SPACE_GROUPS:
                dif.space = SPACE_GROUPS[sym]
            else:
                sys.stdout.write(f"#DBG: NO_space group = {sym}\n")
        if "ATOM" in line:
            # consume atom lines: non-digit graph start (file_dif.c:166-171)
            i += 1
            while i < n:
                s = lines[i].lstrip()
                if not s or s[0].isdigit():
                    break
                i += 1
            continue  # current line re-examined for WAVELENGTH/2-THETA
        if "WAVELENGTH" in line:
            m = _NUM.search(line.split("WAVELENGTH", 1)[1])
            if m:
                dif.lam = float(m.group(0))
        if "2-THETA" in line and dif.n_peaks == 0:
            i += 1
            while i < n:
                s = lines[i].lstrip()
                if not s or not s[0].isdigit():
                    break
                vals = _floats(s, 2)
                if vals is None:
                    break
                dif.n_peaks += 1
                i += 1
            continue
        i += 1
    if dif.n_peaks == 0:
        return None  # mandatory (file_dif.c:325)
    return dif


def read_raw(path: str, dif: Dif) -> bool:
    """Parse the XY raw spectrum (read_raw, file_dif.c:332-379)."""
    try:
        fp = open(path, "r", errors="replace")
    except OSError:
        sys.stderr.write(f"Error opening file: {path}\n")
        return False
    with fp:
        lines = fp.read().splitlines()
    started = False
    for line in lines:
        if not started:
            if line[:1].isdigit():
                started = True
            else:
                continue
        vals = _floats(line, 2)
        if vals is None:
            continue  # permissive on bad lines (file_dif.c:373-375)
        dif.raw_t.append(vals[0])
        dif.raw_i.append(vals[1])
    return started and bool(dif.raw_t)


def dif_2_sample(dif: Dif, fp, n_inputs: int, n_outputs: int) -> bool:
    """Write one sample (dif_2_sample, file_dif.c:425-480)."""
    if dif is None or n_inputs == 0 or n_outputs == 0:
        return False
    n_bins = n_inputs - 1
    interval = (MAX_THETA - MIN_THETA) / n_bins
    bins = [0.0] * n_bins
    # the reference writes the [input] header BEFORE integrating, so an
    # all-zero spectrum leaves a partial file behind (file_dif.c:437-459);
    # behavior kept
    fp.write(f"[input] {n_inputs}\n")
    j = 0
    npts = len(dif.raw_t)
    while j < npts and dif.raw_t[j] < MIN_THETA:
        j += 1
    hi = MIN_THETA + interval
    max_i = 0.0
    for b in range(n_bins):
        acc = 0.0
        while j < npts and dif.raw_t[j] < hi:
            acc += dif.raw_i[j]
            j += 1
        hi += interval
        bins[b] = acc
        if acc > max_i:
            max_i = acc
    if max_i == 0.0:
        return False
    fp.write(f"{dif.temp / 273.15:7.5f}")
    for b in bins:
        fp.write(f" {b / max_i:7.5f}")
    fp.write("\n")
    fp.write(f"[output] {n_outputs}\n")
    # one-hot at space-1; space 0 (unknown) leaves every slot at -1
    # (file_dif.c:468-476)
    fp.write("1.0" if dif.space == 1 else "-1.0")
    for idx in range(1, n_outputs):
        fp.write(" 1.0" if idx == dif.space - 1 else " -1.0")
    fp.write("\n")
    return True


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    n_inputs = n_outputs = 0
    rruff_dir = None
    sample_dir = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("-") and len(a) > 1:
            c = a[1]
            if c == "h":
                sys.stdout.write(
                    "usage: pdif rruff_directory -i n_in -o n_out "
                    "[-s sample_dir]\n")
                return 0
            if c in ("i", "o", "s"):
                value = a[2:] if len(a) > 2 else (
                    argv[i + 1] if i + 1 < len(argv) else "")
                if len(a) <= 2:
                    i += 1
                if c == "s":
                    sample_dir = value
                else:
                    digits = re.match(r"\d+", value.strip())
                    if not digits or int(digits.group(0)) == 0:
                        sys.stderr.write(
                            f"syntax error: bad -{c} parameter!\n")
                        return 1
                    if c == "i":
                        n_inputs = int(digits.group(0)) + 1  # + temperature
                    else:
                        n_outputs = int(digits.group(0))
            else:
                sys.stderr.write("syntax error: unrecognized option!\n")
                return 1
        else:
            if rruff_dir is not None:
                sys.stderr.write("syntax error: too many parameters!\n")
                return 1
            rruff_dir = a
        i += 1
    if rruff_dir is None or n_inputs == 0 or n_outputs == 0:
        sys.stderr.write("syntax error: missing parameters!\n")
        return 1
    if sample_dir is None:
        sample_dir = "./samples"
    sys.stdout.write(f">> received: {rruff_dir} -i {n_inputs} "
                     f"-o {n_outputs} -s {sample_dir}\n")
    if not os.path.isdir(sample_dir):
        sys.stderr.write(f"ERROR: can't open directory: {sample_dir}\n")
        return 1
    dif_dir = os.path.join(rruff_dir, "dif")
    try:
        names = sorted(f for f in os.listdir(dif_dir)
                       if not f.startswith("."))
    except OSError:
        sys.stderr.write(f"ERROR: can't open directory: {dif_dir}/\n")
        return 1
    for name in names:
        sys.stdout.write(f"Processing file: {name}\n")
        dif = read_dif(os.path.join(dif_dir, name))
        if dif is None:
            sys.stderr.write(f"ERROR:  reading {name} file! SKIP\n")
            continue
        if dif.lam == 0.710730:  # Mo wavelength (prepare_dif.c:226)
            sys.stderr.write(
                f"ERROR:  file {name} has wavelength of 0.710730! SKIP\n")
            continue
        raw_path = os.path.join(rruff_dir, "raw", name)
        if not read_raw(raw_path, dif):
            sys.stderr.write(f"ERROR: reading {raw_path} file! SKIP\n")
            continue
        out_path = os.path.join(sample_dir, name)
        try:
            with open(out_path, "w") as fp:
                if not dif_2_sample(dif, fp, n_inputs, n_outputs):
                    sys.stderr.write(
                        f"ERROR: writting {out_path} sample file!\n")
        except OSError:
            sys.stderr.write(
                f"ERROR: opening {out_path} sample file for WRITE!\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
