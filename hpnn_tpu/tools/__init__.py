"""Data-preparation tools: pmnist (MNIST idx -> samples) and pdif
(RRUFF DIF/XY -> XRD samples), rebuilds of the reference converters in
/root/reference/tutorials/mnist/prepare_mnist.c and
/root/reference/tutorials/ann/{prepare_dif.c,file_dif.c}."""
