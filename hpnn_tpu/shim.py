"""Python entry points for the native C shim (native/hpnn_shim.c).

The C library serves the reference's FULL ``_NN(a,b)`` surface
(``/root/reference/include/libhpnn.h:123-228``); every call lands here or
in :mod:`hpnn_tpu.api` / :mod:`hpnn_tpu.runtime`.  These helpers exist so
the C side stays a dumb dispatcher: enum<->string mapping, lazy handle
creation, and the varargs-unpacked kernel lifecycle all live in Python.

Enum values mirror the reference header exactly (nn_type, nn_train --
``libhpnn.h:51-67``); the C shim passes the raw ints.
"""

from __future__ import annotations

from .api import NNDef, dump_kernel_def
from .io.conf import (
    NN_TRAIN_BP,
    NN_TRAIN_BPM,
    NN_TRAIN_CG,
    NN_TRAIN_SPLX,
    NN_TRAIN_UKN,
    NN_TYPE_ANN,
    NN_TYPE_LNN,
    NN_TYPE_SNN,
    NN_TYPE_UKN,
    NNConf,
    dump_conf,
)
from .io.kernel_io import load_kernel
from .io.samples import read_sample
from .models.kernel import generate_kernel
from .utils.nn_log import nn_out

_TYPE_TO_INT = {NN_TYPE_ANN: 0, NN_TYPE_LNN: 1, NN_TYPE_SNN: 2,
                NN_TYPE_UKN: -1}
_INT_TO_TYPE = {v: k for k, v in _TYPE_TO_INT.items()}
_TRAIN_TO_INT = {NN_TRAIN_BP: 0, NN_TRAIN_BPM: 1, NN_TRAIN_CG: 2,
                 NN_TRAIN_SPLX: 3, NN_TRAIN_UKN: -1}
_INT_TO_TRAIN = {v: k for k, v in _TRAIN_TO_INT.items()}


def new_nndef() -> NNDef:
    """A blank handle for _NN(init,conf)-style construction."""
    return NNDef(conf=NNConf())


def conf_as_tuple(nn: NNDef):
    """Mirror-sync pull: (name, type, need_init, seed, f_kernel, train,
    samples, tests) with enums as reference ints."""
    c = nn.conf
    return (c.name, _TYPE_TO_INT.get(c.type, -1), int(bool(c.need_init)),
            int(c.seed), c.f_kernel, _TRAIN_TO_INT.get(c.train, -1),
            c.samples, c.tests)


def conf_set(nn: NNDef, key: str, value) -> None:
    """Mirror-sync push from the C accessors; enum ints map to strings."""
    c = nn.conf
    if key == "type":
        c.type = _INT_TO_TYPE.get(int(value), NN_TYPE_UKN)
    elif key == "train":
        c.train = _INT_TO_TRAIN.get(int(value), NN_TRAIN_UKN)
    elif key == "need_init":
        c.need_init = bool(value)
    elif key == "seed":
        c.seed = int(value)
    elif key in ("name", "f_kernel", "samples", "tests"):
        setattr(c, key, value)
    else:  # pragma: no cover - C side only passes the keys above
        raise KeyError(key)


def generate_kernel_dims(nn: NNDef, n_inputs: int, n_outputs: int,
                         hiddens) -> bool:
    """_NN(generate,kernel) (libhpnn.c:954-980): build from explicit dims,
    honoring conf.seed and writing the effective seed back (the reference
    passes &_CONF.seed into ann_generate)."""
    if nn.conf.type not in (NN_TYPE_ANN, NN_TYPE_SNN):
        return False
    if n_inputs <= 0 or n_outputs <= 0 or not hiddens:
        return False
    kernel, eff_seed = generate_kernel(
        nn.conf.seed, int(n_inputs), [int(h) for h in hiddens],
        int(n_outputs), name="(null)")
    nn.conf.seed = eff_seed
    nn.kernel = kernel
    nn_out(f"[CPU] ANN total allocation: {kernel.allocation_bytes} "
           "(bytes)\n")
    return True


def load_kernel_file(nn: NNDef) -> bool:
    """_NN(load,kernel) (libhpnn.c:981-996)."""
    if nn.conf.f_kernel is None:
        return False
    if nn.conf.type not in (NN_TYPE_ANN, NN_TYPE_SNN):
        return False
    kernel = load_kernel(nn.conf.f_kernel)
    if kernel is None:
        return False
    nn.kernel = kernel
    nn_out(f"[CPU] ANN total allocation: {kernel.allocation_bytes} "
           "(bytes)\n")
    return True


def free_kernel(nn: NNDef) -> None:
    """_NN(free,kernel) (libhpnn.c:941-953)."""
    nn.kernel = None


def dump_kernel_to(nn: NNDef, pyfile) -> bool:
    if nn.kernel is None:
        return False
    return dump_kernel_def(nn, pyfile)


def dump_conf_to(nn: NNDef, pyfile) -> None:
    """_NN(dump,conf) (libhpnn.c:885-937)."""
    dump_conf(nn.conf, pyfile, kernel=nn.kernel)


def get_n_hiddens(nn: NNDef) -> int:
    return nn.kernel.n_hiddens if nn.kernel else 0


def get_h_neurons(nn: NNDef, layer: int) -> int:
    """_NN(get,h_neurons): neuron count of hidden layer `layer`
    (0-based index into the hidden stack, libhpnn.c:1040-1053)."""
    if nn.kernel is None:
        return 0
    hid = nn.kernel.hiddens
    if layer >= len(hid):
        return 0
    return int(hid[int(layer)])


def read_sample_lists(path: str):
    """_NN(read,sample): (list_in, list_out) or None on failure."""
    vec_in, vec_out = read_sample(path)
    if vec_in is None or vec_out is None:
        return None
    return [float(v) for v in vec_in], [float(v) for v in vec_out]
