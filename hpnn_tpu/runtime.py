"""Library runtime: capability report, init/deinit, resource knobs.

TPU-native replacement of the reference's runtime singleton
(``/root/reference/src/libhpnn.c:58-539``).  The reference compiles a
capability bitmask (OMP/MPI/CUDA/CUBLAS/PBLAS/SBLAS,
``include/libhpnn.h:26-35``) and initializes each subsystem; here the
subsystems are JAX/XLA constructs:

* MPI init          -> ``jax.distributed.initialize()`` (multi-host DCN)
* CUDA init + probe -> PJRT client init; device discovery via jax.devices()
* stream pool       -> owned by XLA; the knob survives as a no-op alias
* BLAS threads      -> XLA host threadpool; no-op alias

The `_NN(set/get,...)` knob surface is kept callable so reference-driven
programs (and the C shim) work unchanged: setters store the value and warn
that XLA owns the resource where applicable.
"""

from __future__ import annotations

import dataclasses
import os

from .utils import nn_log

# capability bits: reference values (include/libhpnn.h:26-35) + TPU additions
NN_CAP_NONE = 0
NN_CAP_OMP = 1 << 0
NN_CAP_MPI = 1 << 1
NN_CAP_CUDA = 1 << 2
NN_CAP_CUBLAS = 1 << 3
NN_CAP_PBLAS = 1 << 5
NN_CAP_SBLAS = 1 << 6
# new bits, disjoint from the reference's
NN_CAP_XLA = 1 << 8
NN_CAP_TPU = 1 << 9
NN_CAP_X64 = 1 << 10


@dataclasses.dataclass
class NNRuntime:
    """The `nn_runtime` singleton state (libhpnn.c:58-90)."""

    capability: int = 0
    nn_dry: bool = False
    nn_num_threads: int = 1   # -O knob; XLA owns host threads (alias)
    nn_num_blas: int = 1      # -B knob; alias
    nn_num_tasks: int = 1     # MPI task count -> jax.process_count()
    n_devices: int = 1        # CUDA gpu/stream pool -> jax.device_count()
    n_streams: int = 1        # -S knob; alias (XLA owns streams)
    initialized: bool = False


lib_runtime = NNRuntime()


def return_capabilities() -> int:
    """Compile-time capability probe (libhpnn.c:113-134): here resolved at
    runtime from the JAX backend."""
    cap = NN_CAP_XLA
    try:
        import jax

        if any(d.platform == "tpu" for d in jax.devices()):
            cap |= NN_CAP_TPU
        if jax.config.jax_enable_x64:
            cap |= NN_CAP_X64
        if jax.process_count() > 1:
            cap |= NN_CAP_MPI  # multi-host: the MPI capability analog
    except Exception:
        pass
    return cap


def apply_env_platforms() -> None:
    """Honor JAX_PLATFORMS even when a site hook already registered a
    platform plugin and overwrote the jax_platforms config (the env var is
    read only at first import, which such a hook preempts)."""
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        import jax

        jax.config.update("jax_platforms", env_platforms)


def init_runtime() -> None:
    """_NN(init,runtime) (libhpnn.c:160-172)."""
    global lib_runtime
    lib_runtime = NNRuntime()
    nn_log.set_verbosity(0)


def enable_compilation_cache(cache_dir: str | None = None) -> None:
    """Persistent on-disk compilation cache for every driver process.

    The tutorial workflow launches a FRESH process per training round
    (``tutorials/mnist/tutorial.bash`` round loop, mirroring the
    reference's), so without this every round re-pays jit + Mosaic
    compilation -- the dominant cold-round cost (VERDICT r2 "weak" 1).
    The same cost dominates ``serve_nn`` restarts: every batch bucket
    recompiles during warmup unless this cache persists across processes
    (the CLI's ``--compile-cache DIR`` passes ``cache_dir`` explicitly).

    An explicit ``cache_dir`` argument wins over everything, including
    the HPNN_NO_COMPILE_CACHE opt-out (the caller typed a flag; honor
    it).  Otherwise: opt out with HPNN_NO_COMPILE_CACHE=1; relocate with
    HPNN_CACHE_DIR; an explicit JAX_COMPILATION_CACHE_DIR (jax's own env
    var) wins over the HPNN default.
    """
    if cache_dir is None:
        if os.environ.get("HPNN_NO_COMPILE_CACHE"):
            return
        if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            return  # jax already configured from its own env var
    import jax

    cache_dir = cache_dir or os.environ.get("HPNN_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "hpnn_tpu", "jax_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # the convergence kernels compile in ~1s each; default thresholds
        # (>=2 min compile) would cache nothing we care about
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as exc:  # cache is an optimization, never fatal
        nn_log.nn_warn(f"compilation cache disabled: {exc}\n")


def init_all(init_verbose: int = 0) -> int:
    """_NN(init,all) (libhpnn.c:326-347): bring up the device runtime.

    Enables fp64 (the reference is fp64 throughout, common.h:153) and
    discovers the device topology.  Returns 0 on success, -1 on failure.
    """
    init_runtime()
    nn_log.set_verbosity(init_verbose)
    from .obs import trace as obs_trace

    # HPNN_TRACE=1: span tracing + flight recorder from process start
    # (the serve CLI can also enable it later via --trace)
    obs_trace.enable_from_env()
    try:
        import jax

        apply_env_platforms()
        jax.config.update("jax_enable_x64", True)
        enable_compilation_cache()
        if os.environ.get("HPNN_DISTRIBUTED"):  # multi-host opt-in
            # the TPU analog of _NN(init,MPI) (libhpnn.c:182-200): join
            # the multi-process coordination service.  Cluster launchers
            # (GKE/SLURM) are auto-detected by jax; manual topologies --
            # like the reference's `mpirun -n N` -- give the coordinator
            # explicitly via HPNN_COORDINATOR / HPNN_NUM_PROCESSES /
            # HPNN_PROCESS_ID.
            kwargs = {}
            if os.environ.get("HPNN_COORDINATOR"):
                missing = [v for v in
                           ("HPNN_NUM_PROCESSES", "HPNN_PROCESS_ID")
                           if v not in os.environ]
                if missing:
                    raise RuntimeError(
                        "HPNN_COORDINATOR requires "
                        + " and ".join(missing)
                        + " to be set (coordinator host:port, total "
                        "process count, this process's 0-based id)")
                kwargs = dict(
                    coordinator_address=os.environ["HPNN_COORDINATOR"],
                    num_processes=int(os.environ["HPNN_NUM_PROCESSES"]),
                    process_id=int(os.environ["HPNN_PROCESS_ID"]),
                )
            try:
                # the CPU client only wires cross-process collectives
                # when a collectives implementation is selected BEFORE
                # the backend comes up; without it every multi-process
                # jit dies with "Multiprocess computations aren't
                # implemented on the CPU backend".  TPU/GPU ignore the
                # flag, and jaxlibs without gloo raise -- they keep the
                # single-host behaviour they had.
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass
            jax.distributed.initialize(**kwargs)
        devs = jax.devices()
        lib_runtime.n_devices = len(devs)
        lib_runtime.nn_num_tasks = jax.process_count()
        lib_runtime.capability = return_capabilities()
        nn_log.nn_out(
            f"runtime: {len(devs)} {devs[0].platform} device(s), "
            f"{lib_runtime.nn_num_tasks} process(es)\n")
        ok = True
    except Exception as exc:  # pragma: no cover - backend init failure
        nn_log.nn_error(f"device runtime init failed: {exc}\n")
        ok = False
    nn_log.set_verbosity(0)
    lib_runtime.initialized = ok
    return 0 if ok else -1


def deinit_all() -> int:
    """_NN(deinit,all) (libhpnn.c:395-407): XLA owns teardown; reset state."""
    init_runtime()
    return 0


def toggle_dry() -> None:
    """_NN(toggle,dry): the reference's XOR is a no-op bug
    (``nn_dry^=nn_dry`` always yields FALSE, libhpnn.c:88-90).  Behavior
    preserved: toggling dry mode never enables it."""
    lib_runtime.nn_dry = False


# --- knob aliases (set/get triplets, libhpnn.c:409-539) --------------------

def unset_capability(bit: int) -> None:
    """_NN(unset,capability) (libhpnn.c:135-159): mask a capability off."""
    lib_runtime.capability &= ~int(bit)


def init_omp() -> bool:
    """_NN(init,OMP): host threads are XLA-owned; nothing to bring up."""
    return True


def init_mpi() -> bool:
    """_NN(init,MPI): multi-process joins in init_all (HPNN_DISTRIBUTED);
    a standalone call is a no-op success like a 1-task MPI world."""
    return True


def init_cuda() -> bool:
    """_NN(init,CUDA): PJRT client comes up with the first jax call."""
    return True


def init_blas() -> bool:
    """_NN(init,BLAS): XLA dot; no backend selection needed."""
    return True


def deinit_omp() -> bool:
    return True


def deinit_mpi() -> bool:
    return True


def deinit_cuda() -> bool:
    return True


def deinit_blas() -> bool:
    return True


def set_mpi_tasks(n: int) -> bool:
    """_NN(set,mpi_tasks): the process count is fixed by the launcher
    (jax.distributed); the knob is stored for reporting only."""
    nn_log.nn_warn("process count is owned by the launcher; "
                   "stored for reporting only\n")
    lib_runtime.nn_num_tasks = max(1, int(n))
    return True


def set_n_gpu(n: int) -> bool:
    """_NN(set,n_gpu): device count is owned by PJRT; alias knob."""
    nn_log.nn_warn("device count is owned by the platform runtime; "
                   "stored for reporting only\n")
    lib_runtime.n_devices = max(1, int(n))
    return True


def get_n_gpu() -> int:
    return lib_runtime.n_devices


def get_cuda_streams() -> int:
    return lib_runtime.n_streams


def set_omp_threads(n: int) -> bool:
    lib_runtime.nn_num_threads = max(1, int(n))
    return True


def get_omp_threads() -> int:
    return lib_runtime.nn_num_threads


def set_omp_blas(n: int) -> bool:
    lib_runtime.nn_num_blas = max(1, int(n))
    return True


def get_omp_blas() -> int:
    return lib_runtime.nn_num_blas


def set_cuda_streams(n: int) -> bool:
    """Stream-pool knob (libhpnn.c:471-505): XLA owns streams; the value is
    kept as a shard-count hint for the parallel layer."""
    lib_runtime.n_streams = max(1, int(n))
    return True


def get_mpi_tasks() -> int:
    return lib_runtime.nn_num_tasks


def get_curr_mpi_task() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_n_devices() -> int:
    return lib_runtime.n_devices
