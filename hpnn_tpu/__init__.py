"""hpnn_tpu -- a TPU-native rebuild of libhpnn (ovhpa/hpnn).

A JAX/XLA/Pallas framework for on-the-fly training of small fully-connected
neural networks, with the reference's complete capability surface (ANN/SNN
model families, BP/BPM training, text .conf/.kernel formats, stdout grammar)
re-designed TPU-first:

* compute is jit-compiled XLA (fp64 parity path, fp32/bf16 throughput path)
* the per-sample train-to-convergence loop is a single on-device
  ``lax.while_loop`` (no host round-trip per iteration)
* distribution is a ``jax.sharding.Mesh`` -- row-sharded tensor parallelism
  (the reference's MPI strategy) and batched data parallelism (new) via
  collectives compiled by XLA over ICI/DCN.

Package map:
    utils/     glibc-compatible PRNG, verbosity-gated logging
    io/        .conf, .kernel/.opt checkpoints, sample files
    models/    the MLP kernel container + seeded generation
    ops/       jit step functions: forward, error, deltas, BP/BPM, while-loop
    parallel/  mesh runtime, TP/DP shardings, collectives
    ckpt/      crash-safe snapshots, bit-exact resume, model lifecycle
    serve/     long-lived inference serving (registry, batcher, HTTP)
    api.py     nn_def-level driver API (train_kernel / run_kernel)
"""

__version__ = "0.1.0"

from . import io, models, runtime, utils

__all__ = ["io", "models", "runtime", "utils", "__version__"]


def __getattr__(name):
    # ops/api/cli/parallel pull in jax; import lazily so pure-IO use stays light
    if name in ("ops", "api", "cli", "parallel", "ckpt", "serve"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
