"""The MLP "kernel" -- the reference's single model data structure.

The reference's ``kernel_ann`` (``/root/reference/include/libhpnn/ann.h:35-55``)
is a stack of dense layers without biases: each layer is a row-major weight
matrix W of shape (n_neurons, n_inputs) and an activation vector.  The same
structure backs all three model families (ANN sigmoid output, SNN softmax
output, LNN linear output -- the latter declared but unimplemented in the
reference, ``/root/reference/src/libhpnn.c:975-978``).

Here the host-side kernel is a plain container of float64 numpy arrays; the
device-side compute path (hpnn_tpu.ops) consumes ``kernel.weights`` as a tuple
pytree of jnp arrays.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Sequence

import numpy as np

from ..utils.glibc_random import RAND_MAX, GlibcRandom


def output_head(kind: str) -> str:
    """The output-layer nonlinearity of a model family: ANN sigmoid,
    SNN softmax, LNN linear (the regression head, hpnn_tpu.ops.steps)."""
    return {"SNN": "softmax", "LNN": "linear"}.get(kind, "sigmoid")


def is_regression(kind: str) -> bool:
    """Regression families score on MSE, not argmax-class error; drives
    run_kernel's output grammar and the jobs auto-promote objective."""
    return output_head(kind) == "linear"


@dataclasses.dataclass
class Kernel:
    """Host-side MLP parameter container.

    weights[l] has shape (N_l, M_l) with M_0 == n_inputs and
    N_{last} == n_outputs; layer l computes act(W_l @ v_{l-1}).
    """

    name: str
    weights: list[np.ndarray]
    momentum: list[np.ndarray] | None = None  # dw buffers (BPM), ann.c:1876-1939

    @property
    def n_inputs(self) -> int:
        return int(self.weights[0].shape[1])

    @property
    def n_outputs(self) -> int:
        return int(self.weights[-1].shape[0])

    @property
    def hiddens(self) -> list[int]:
        return [int(w.shape[0]) for w in self.weights[:-1]]

    @property
    def n_hiddens(self) -> int:
        return len(self.weights) - 1

    @property
    def params(self) -> list[int]:
        """The `[param]` line: n_inputs, hidden sizes..., n_outputs."""
        return [self.n_inputs, *self.hiddens, self.n_outputs]

    def momentum_init(self) -> None:
        """Allocate + zero dw buffers (ann_momentum_init, ann.c:1876-1890)."""
        from ..utils.nn_log import nn_out

        self.momentum = [np.zeros_like(w) for w in self.weights]
        # accounting line (ann.c:1904): the dw pointer array (8 bytes per
        # layer) plus each dw matrix at 8 bytes per weight
        n_bytes = 8 * len(self.weights) + 8 * sum(
            int(w.size) for w in self.weights)
        nn_out(f"[CPU] MOMENTUM ALLOC: {n_bytes} (bytes)\n")

    def momentum_free(self) -> None:
        self.momentum = None

    @property
    def allocation_bytes(self) -> int:
        """The byte count ann_kernel_allocate reports (ann.c:113-200):
        n_hiddens * sizeof(layer_ann)=24, the max_index scratch, the input
        vector, and every layer's weights+activation vector at 8 bytes each
        (verified against the compiled reference's '[CPU] ANN total
        allocation' line)."""
        n_hiddens = self.n_hiddens
        max_index = max(self.n_inputs, self.n_outputs, *self.hiddens)
        doubles = max_index + self.n_inputs + sum(
            w.shape[0] * w.shape[1] + w.shape[0] for w in self.weights)
        return 24 * n_hiddens + 8 * doubles

    def validate(self) -> bool:
        """Shape-consistency check (ann_validate_kernel, ann.c:862-879)."""
        if not self.weights:
            return False
        for a, b in zip(self.weights, self.weights[1:]):
            if a.shape[0] != b.shape[1]:
                return False
        return True


def generate_kernel(
    seed: int,
    n_inputs: int,
    hiddens: Sequence[int],
    n_outputs: int,
    name: str = "noname",
) -> tuple[Kernel, int]:
    """Random kernel with the reference's exact init stream.

    Reproduces ``ann_generate`` (``/root/reference/src/ann.c:632-766``):
    ``srandom(seed)`` (seed 0 replaced by time()), then each layer's weights
    filled row-major with ``2*(random()/RAND_MAX - 0.5)/sqrt(M)`` -- hidden
    layers first in order, output layer last.

    Returns (kernel, effective_seed) since the reference writes back the
    time()-derived seed into the conf when seed==0 (ann.c:653).
    """
    seed = int(seed)
    if seed == 0:
        seed = int(time.time())
    rng = GlibcRandom(seed)
    dims = [int(n_inputs), *[int(h) for h in hiddens], int(n_outputs)]
    weights: list[np.ndarray] = []
    for m, n in zip(dims[:-1], dims[1:]):
        u = rng.uniform_array(n * m).reshape(n, m)
        weights.append(2.0 * (u - 0.5) / np.sqrt(float(m)))
    return Kernel(name=name, weights=weights), seed
