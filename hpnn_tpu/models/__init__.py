from .kernel import Kernel, generate_kernel

__all__ = ["Kernel", "generate_kernel"]
