"""Trainer registry: the dispatch surface for training algorithms.

The reference hard-codes its trainer dispatch in ``nn_kernel_train``
(``/root/reference/src/libhpnn.c:1193-1291``): BP and BPM run, CG and SPLX
are declared but fall through an "unimplemented" warning
(``libhpnn.c:1253-1257``).  This package keeps that surface byte-identical
by DEFAULT -- the reference trainers stay on api.train_kernel's built-in
routes -- and adds an opt-in registry hosting trainers the reference never
implemented, starting with the batched nonlinear conjugate-gradient
trainer (hpnn_tpu.train.cg, ROADMAP item 4).

Registry entries carry:

* ``native``: False for BP/BPM (api's reference dispatch handles them --
  the entry exists so tooling can enumerate every trainer through ONE
  surface), True for trainers that run through ``run_epoch``;
* ``run_epoch(nn, weights, xs, ts, kind, dtype)``: one whole-corpus
  training epoch; returns the updated weight arrays and leaves
  ``nn.last_epoch_stats`` / ``nn.trainer_state`` refreshed.

Activation is two-level, mirroring the native-LNN gate: the conf opts in
(``[trainer] cg`` / ``--trainer cg``) or the environment does
(``HPNN_TRAINER=cg``).  Without either, a ``[train] CG`` conf keeps the
reference's untrainable fallthrough bytes.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

from ..io.conf import (
    NN_TRAIN_BP,
    NN_TRAIN_BPM,
    NN_TRAIN_CG,
    NN_TYPE_LNN,
)


@dataclasses.dataclass(frozen=True)
class TrainerEntry:
    name: str
    train: str            # the [train] conf value this trainer serves
    native: bool          # True: run_epoch drives the epoch
    description: str
    run_epoch: Callable | None = None


_TRAINERS: dict[str, TrainerEntry] = {}


def register_trainer(entry: TrainerEntry) -> None:
    _TRAINERS[entry.name] = entry


def get_trainer(name: str) -> TrainerEntry:
    return _TRAINERS[name]


def trainer_names() -> list[str]:
    return sorted(_TRAINERS)


def trainer_label(conf) -> str:
    """The trainer label serve/metrics expose per kernel: the registry
    name for the conf's [train] value ("none" when untrainable)."""
    for entry in _TRAINERS.values():
        if entry.train == conf.train:
            return entry.name
    return "none"


def native_lnn(conf) -> bool:
    """Native linear-output LNN opt-in: ``[lnn] native`` / ``--lnn
    native`` or ``HPNN_LNN_NATIVE=1``.  Off, an LNN conf keeps the
    reference's warn-and-SNN-fallthrough byte-for-byte."""
    if conf.type != NN_TYPE_LNN:
        return False
    if getattr(conf, "lnn", "") == "native":
        return True
    return os.environ.get("HPNN_LNN_NATIVE", "") not in ("", "0")


def native_trainer(conf) -> TrainerEntry | None:
    """The native trainer entry driving this conf's training epochs, or
    None when the reference dispatch applies.  Requires BOTH the conf's
    [train] algorithm to have a native registry entry AND the opt-in
    (conf.trainer / HPNN_TRAINER)."""
    want = getattr(conf, "trainer", "") or os.environ.get("HPNN_TRAINER", "")
    if not want or want == "0":
        return None
    entry = _TRAINERS.get(want if want != "native" else "cg")
    if entry is None or not entry.native:
        return None
    return entry if entry.train == conf.train else None


def _register_builtins() -> None:
    from .cg import run_cg_epoch

    register_trainer(TrainerEntry(
        name="bp", train=NN_TRAIN_BP, native=False,
        description="online per-sample backprop to convergence "
                    "(reference dispatch, ann.c:2281-2372)"))
    register_trainer(TrainerEntry(
        name="bpm", train=NN_TRAIN_BPM, native=False,
        description="per-sample backprop with momentum "
                    "(reference dispatch, ann.c:2377-2466)"))
    register_trainer(TrainerEntry(
        name="cg", train=NN_TRAIN_CG, native=True,
        description="batched nonlinear conjugate gradient "
                    "(Polak-Ribiere + restart, on-device line search)",
        run_epoch=run_cg_epoch))


_register_builtins()

__all__ = [
    "TrainerEntry", "register_trainer", "get_trainer", "trainer_names",
    "trainer_label", "native_lnn", "native_trainer",
]
