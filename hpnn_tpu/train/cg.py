"""Batched nonlinear conjugate-gradient trainer (the reference's missing CG).

The reference declares ``NN_TRAIN_CG`` but never implements it
(``/root/reference/src/libhpnn.c:1253-1257``); arXiv:1701.05130 races
exactly this trainer family against per-sample BP.  This implementation is
TPU-shaped end to end:

* the objective is the WHOLE-corpUS mean of the per-sample training error
  (``ops.steps.error`` over ``batched_forward``), so one loss/gradient
  evaluation is a chain of (S, M) @ (M, N) GEMMs -- MXU work, not the
  per-sample GEMV convergence loop BP runs;
* the gradient is ``jax.value_and_grad`` of that same GEMM chain (an
  honest gradient -- CG needs one; the reference BP quirks like the ANN
  dact output factor belong to the per-sample trainers, not here);
* the direction update is Polak-Ribiere with the standard guards:
  ``beta = max(0, <g, g - g_prev> / <g_prev, g_prev>)`` and a restart to
  steepest descent whenever the new direction is not a descent direction
  (restart count carried in the snapshot state);
* the step length comes from an on-device bracketing line search: halve
  until the probe improves on the current loss, double while it keeps
  improving, then a fixed-iteration ternary refine of the bracket -- all
  inside the compiled epoch, zero host round-trips per iteration.

One ``train_kernel`` epoch runs ``HPNN_CG_ITERS`` (default 8) CG
iterations.  State across epochs -- direction, prior gradient, restart
counter -- lives in ``nn.trainer_state`` as flat vectors so the checkpoint
subsystem snapshots/restores it with the same verified-write guarantees as
BPM momentum, and resume is bit-exact (pinned in tests/test_ckpt.py).

Under a ``[batch]`` data-parallel conf the CG state rides the PR-12
optimizer-state layout: flattened to ONE vector, zero-padded to the data
axis and sharded P("data") (``parallel.mesh.flat_state_sharding``), each
replica holding a contiguous 1/N slice.  All placement ops are
value-preserving, so the sharded trajectory is bitwise the single-device
one.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..utils.nn_log import nn_out, nn_warn

# line-search budget: max halvings/doublings while bracketing, then the
# fixed ternary refine depth (2 loss evals per refine step)
_LS_BRACKET_MAX = 24
_LS_REFINE = 12

_CG_ITERS_DEFAULT = 8

_EPOCH_CACHE: dict = {}


def cg_iters_per_epoch() -> int:
    raw = os.environ.get("HPNN_CG_ITERS", "")
    try:
        n = int(raw) if raw else _CG_ITERS_DEFAULT
    except ValueError:
        nn_warn(f"HPNN_CG_ITERS={raw!r} is not an integer; "
                f"using {_CG_ITERS_DEFAULT}\n")
        return _CG_ITERS_DEFAULT
    return max(1, n)


def _line_search(loss, f, d, l0):
    """Bracketing line search along ``d`` from ``f``: returns the step t
    (0.0 when no probe improves on ``l0``)."""
    import jax.numpy as jnp
    from jax import lax

    one = jnp.asarray(1.0, f.dtype)

    def phi(t):
        return loss(f + t * d)

    # shrink: halve until the probe improves on l0
    def s_cond(c):
        _, ft, k = c
        return (ft >= l0) & (k < _LS_BRACKET_MAX)

    def s_body(c):
        t, _, k = c
        t = t * 0.5
        return t, phi(t), k + 1

    t, ft, _ = lax.while_loop(s_cond, s_body,
                              (one, phi(one), jnp.int32(0)))

    # grow: double while the doubled probe keeps improving
    def g_cond(c):
        _, ft, _, ft2, k = c
        return (ft2 < ft) & (k < _LS_BRACKET_MAX)

    def g_body(c):
        _, _, t2, ft2, k = c
        nt = t2 * 2.0
        return t2, ft2, nt, phi(nt), k + 1

    t, ft, t2, _, _ = lax.while_loop(
        g_cond, g_body, (t, ft, t * 2.0, phi(t * 2.0), jnp.int32(0)))

    # ternary refine of [0, t2] (unimodal along the bracket)
    def r_body(_, ab):
        a, b = ab
        m1 = a + (b - a) / 3.0
        m2 = b - (b - a) / 3.0
        keep_lo = phi(m1) <= phi(m2)
        return (jnp.where(keep_lo, a, m1), jnp.where(keep_lo, m2, b))

    a, b = lax.fori_loop(0, _LS_REFINE, r_body,
                         (jnp.zeros_like(t), t2))
    t_star = 0.5 * (a + b)
    ft_star = phi(t_star)
    t_best = jnp.where(ft_star <= ft, t_star, t)
    f_best = jnp.minimum(ft_star, ft)
    return jnp.where(f_best < l0, t_best, jnp.zeros_like(t))


def _compiled_epoch(shapes, kind, n_iters, dtype_name):
    """The jitted CG epoch for one (topology, kind, iters, dtype)."""
    key = (shapes, kind, n_iters, dtype_name)
    fn = _EPOCH_CACHE.get(key)
    if fn is not None:
        return fn

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import TINY, batched_forward, error
    from ..parallel.mesh import unflatten_state

    def loss_of(xs, ts):
        def loss(flat):
            ws = unflatten_state(flat, shapes)
            outs = batched_forward(ws, xs, kind)
            return jnp.mean(error(outs, ts, kind))
        return loss

    def epoch(flat, d, g_prev, have, restarts, xs, ts):
        loss = loss_of(xs, ts)
        e0 = loss(flat)

        def cg_step(carry, _):
            f, d, g_prev, have, restarts = carry
            l, g = jax.value_and_grad(loss)(f)
            gg_prev = jnp.vdot(g_prev, g_prev)
            beta = jnp.maximum(
                0.0, jnp.vdot(g, g - g_prev)
                / jnp.maximum(gg_prev, jnp.asarray(TINY, f.dtype)))
            beta = jnp.where(have, beta, 0.0)
            d_new = -g + beta * d
            descent = jnp.vdot(d_new, g) < 0.0
            d_new = jnp.where(descent, d_new, -g)
            restarts = restarts + (have & ~descent).astype(jnp.int32)
            t_step = _line_search(loss, f, d_new, l)
            f_new = f + t_step * d_new
            return (f_new, d_new, g, jnp.asarray(True), restarts), l

        (f, d, g, _, restarts), _ = lax.scan(
            cg_step, (flat, d, g_prev, have, restarts), None,
            length=n_iters)
        e1 = loss(f)
        gn = jnp.sqrt(jnp.vdot(g, g))
        return f, d, g, e0, e1, gn, restarts

    fn = jax.jit(epoch)
    _EPOCH_CACHE[key] = fn
    return fn


def _load_state(nn, total: int, pad_to: int, dtype):
    """nn.trainer_state -> (d, g, have, restarts) padded flat arrays.
    A size mismatch (topology changed under the snapshot) warns and
    restarts CG from steepest descent."""
    import jax.numpy as jnp

    st = getattr(nn, "trainer_state", None)
    zeros = jnp.zeros((total + (-total) % max(1, pad_to),), dtype)
    if not st:
        return zeros, zeros, False, 0
    d = np.asarray(st.get("cg_d", ()), np.float64).reshape(-1)
    g = np.asarray(st.get("cg_g", ()), np.float64).reshape(-1)
    meta = np.asarray(st.get("cg_meta", (0, 0, 0)), np.int64).reshape(-1)
    if d.size != total or g.size != total:
        nn_warn("CG state size mismatch; restarting from steepest "
                "descent\n")
        return zeros, zeros, False, 0
    pad = zeros.shape[0] - total
    if pad:
        d = np.concatenate([d, np.zeros((pad,), np.float64)])
        g = np.concatenate([g, np.zeros((pad,), np.float64)])
    return (jnp.asarray(d, dtype), jnp.asarray(g, dtype),
            bool(meta[0]) if meta.size else False,
            int(meta[1]) if meta.size > 1 else 0)


def run_cg_epoch(nn, weights, xs, ts, kind, dtype):
    """One CG training epoch over the staged corpus; returns the updated
    weight tuple.  Refreshes ``nn.last_epoch_stats`` (mean corpus error
    after the epoch, the manifest-trajectory hook) and
    ``nn.trainer_state`` (direction / prior gradient / restart counter,
    unpadded f64 -- the snapshot payload)."""
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import (flat_state_sharding, flatten_state,
                                 make_mesh)

    t0 = time.perf_counter()
    conf = nn.conf
    shapes = tuple(tuple(int(n) for n in w.shape) for w in weights)
    total = int(sum(int(np.prod(sh)) for sh in shapes))
    n_iters = cg_iters_per_epoch()

    # [batch] DP route: shard the flat CG state P("data") (PR-12 layout)
    n_data = 1
    sharding = None
    if getattr(conf, "batch", 0) > 0:
        from ..api import _dp_device_count, slice_devices

        n_data = _dp_device_count()
        if n_data > 1:
            mesh = make_mesh(n_data=n_data, n_model=1,
                             devices=slice_devices())
            sharding = flat_state_sharding(mesh)

    flat = flatten_state([jnp.asarray(w, dtype) for w in weights],
                         pad_to=n_data)
    d, g, have, restarts = _load_state(nn, total, n_data, dtype)
    if sharding is not None:
        flat = jax.device_put(flat, sharding)
        d = jax.device_put(d, sharding)
        g = jax.device_put(g, sharding)

    fn = _compiled_epoch(shapes, kind, n_iters, jnp.dtype(dtype).name)
    flat, d, g, e0, e1, gn, restarts = fn(
        flat, d, g, jnp.asarray(bool(have)), jnp.int32(restarts),
        jnp.asarray(xs, dtype), jnp.asarray(ts, dtype))

    e0, e1, gn = float(e0), float(e1), float(gn)
    n_restarts = int(restarts)
    s = int(xs.shape[0])
    dt = time.perf_counter() - t0
    # one line per epoch (new-capability grammar -- deterministic, so the
    # resume byte-parity pin covers it; wall time goes to DBG only)
    nn_out(f"TRAINING CG\t samples={s:8d} iters={n_iters:4d} "
           f"E0={e0:15.10f} E1={e1:15.10f} |g|={gn:15.10f} "
           f"restarts={n_restarts:4d}\n")
    from ..utils.nn_log import nn_dbg

    nn_dbg(f"CG epoch wall {dt:.3f} s\n")

    flat_h = np.asarray(flat, np.float64)[:total]
    d_h = np.asarray(d, np.float64)[:total]
    g_h = np.asarray(g, np.float64)[:total]
    nn.trainer_state = {
        "cg_d": d_h,
        "cg_g": g_h,
        "cg_meta": np.asarray([1, n_restarts, n_iters], np.int64),
    }
    nn.last_epoch_stats = {"samples": s, "success": 0,
                           "mean_init": e0, "mean_final": e1}

    lo, out = 0, []
    for sh in shapes:
        n = int(np.prod(sh))
        out.append(jnp.asarray(flat_h[lo:lo + n].reshape(sh), dtype))
        lo += n
    return tuple(out)
