"""`nn_def`-level driver API: configure / train_kernel / run_kernel.

TPU-native rebuild of the reference's orchestration layer
(``/root/reference/src/libhpnn.c:540-1536``).  The host-visible behavior --
the ``.conf`` -> kernel workflow, the seeded shuffle, and the per-sample
stdout grammar the tutorials scrape with grep -- is reproduced exactly, but
the execution model is redesigned TPU-first:

* the reference re-reads and re-parses every sample text file per epoch and
  trains it in a host loop (``libhpnn.c:1221-1288``); we bulk-load the sample
  directory once into stacked (S, n) arrays and run the WHOLE epoch as one
  jit-compiled ``lax.scan`` on device (hpnn_tpu.ops.train_epoch) -- zero host
  round-trips per sample;
* inference stacks the whole test set into one device launch
  (``ops.run_batch``: a scanned per-row GEMV chain, keeping the
  reference's per-file bit-independence -- see its docstring) instead of
  one host-driven launch per file (``libhpnn.c:1426``);
* the per-sample console lines are reconstructed afterwards from the scanned
  statistics, byte-identical to the reference's printf stream.

Stdout grammar (a de-facto API, see SURVEY.md section 5):

  training, one line per sample (NN_OUT so verbose>1; ann.c:2322-2366):
    "NN: TRAINING FILE: %16.16s\t init=%15.10f"  then " OK"/" NO"  then
    " N_ITER=%8i final=%15.10f"  then " SUCCESS!\n"/" FAIL!\n"
    -- except snn_train_BP which ends " final=%15.10f\n" with no verdict
       (``snn.c:1496-1499``).
  testing (libhpnn.c:1388-1517):
    "NN: TESTING FILE: %16.16s\t"  then for ANN " [PASS]\n" or
    " [FAIL idx=%i]\n"; for SNN " BEST CLASS idx=%i P=%15.10f" first.

Quirks preserved on purpose (each cited):

* skipped unreadable samples leave the "TRAINING FILE: name\t" line without
  a newline, so the next line concatenates (``libhpnn.c:1230-1242`` prints
  the header before the read and skips without terminating it);
* the ANN test verdict initializes its target index to TRUE(=1), so a test
  file with no target > 0.5 "passes" iff the argmax is 1
  (``libhpnn.c:1443-1450``);
* guess starts at n_outputs, so an all-<= -1 output vector fails with an
  out-of-range guess (``libhpnn.c:1443``);
* the shuffle consumes glibc random() draws with replacement-retry
  (``libhpnn.c:1218-1229``) -- reproduced stream-exactly via
  utils.glibc_random.
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from .io import corpus as corpus_io
from .io.conf import (
    NN_TRAIN_BP,
    NN_TRAIN_BPM,
    NN_TYPE_ANN,
    NN_TYPE_LNN,
    NN_TYPE_SNN,
    NN_TYPE_UKN,
    NNConf,
    load_conf,
)
from .io.kernel_io import dump_kernel, load_kernel
from .io.samples import list_sample_dir
from .models.kernel import Kernel, generate_kernel
from .utils import nn_log
from .utils.glibc_random import GlibcRandom, shuffled_indices
from .utils.nn_log import nn_cout, nn_dbg, nn_error, nn_out, nn_warn


@dataclasses.dataclass
class NNDef:
    """The reference's `nn_def` handle (include/libhpnn.h:78-89)."""

    conf: NNConf
    kernel: Kernel | None = None
    # persistent shuffle stream for in-process multi-epoch training
    # (ckpt.trainer): when set, every train_kernel call CONTINUES this
    # glibc stream instead of re-seeding -- and the checkpoint subsystem
    # snapshots/restores its words for bit-exact resume.  None keeps the
    # reference's fresh-srandom-per-process behavior.
    shuffle_rng: object | None = None
    # summary of the last completed training epoch (mean final error
    # etc.), read by the checkpoint manager for the manifest's error
    # trajectory; None until an epoch has run
    last_epoch_stats: dict | None = None
    # native-trainer carry (hpnn_tpu.train): e.g. the CG direction /
    # prior gradient / restart counter.  Snapshotted and restored by the
    # checkpoint subsystem for bit-exact resume, like BPM momentum.
    trainer_state: dict | None = None

    # accessor parity with _NN(get,n_inputs) etc. (libhpnn.c:1013-1066)
    @property
    def n_inputs(self) -> int:
        return self.kernel.n_inputs if self.kernel else 0

    @property
    def n_outputs(self) -> int:
        return self.kernel.n_outputs if self.kernel else 0

    # the get/set/return triplet family (libhpnn.c:544-657); the reference
    # exposes each conf field through the _NN surface
    def get_name(self) -> str | None:
        return self.conf.name

    def set_name(self, name: str) -> None:
        self.conf.name = name

    def get_type(self) -> str:
        return self.conf.type

    def set_type(self, kind: str) -> None:
        self.conf.type = kind

    def get_seed(self) -> int:
        return self.conf.seed

    def set_seed(self, seed: int) -> None:
        self.conf.seed = int(seed)

    def get_train(self) -> str:
        return self.conf.train

    def set_train(self, train: str) -> None:
        self.conf.train = train

    def get_sample_dir(self) -> str | None:
        return self.conf.samples

    def set_sample_dir(self, path: str) -> None:
        self.conf.samples = path

    def get_test_dir(self) -> str | None:
        return self.conf.tests

    def set_test_dir(self, path: str) -> None:
        self.conf.tests = path


def configure(path: str) -> NNDef | None:
    """_NN(load,conf): parse the .conf then generate or load the kernel
    (``libhpnn.c:658-884``).

    Multi-process: ends with the coordinated load bailout -- the
    reference's rank-0 handshake (``ann.c:242-248,549-556``) re-expressed
    as an all-process status gate, so a conf/kernel parse failure on ANY
    process makes EVERY process return None cleanly instead of leaving
    the others blocked in a collective (VERDICT r2 missing 4)."""
    nn = _configure_local(path)
    from .parallel.coord import agree_all

    fp = ((nn.n_inputs, nn.n_outputs,
           sum(int(np.asarray(w).size) for w in nn.kernel.weights))
          if nn is not None and nn.kernel is not None else (0, 0, 0))
    if not agree_all(nn is not None, fp):
        return None
    return nn


def _configure_local(path: str) -> NNDef | None:
    conf = load_conf(path)
    if conf is None:
        return None
    if conf.need_init:
        if conf.type == NN_TYPE_UKN:
            nn_error("no kernel type given!\n")
            return None
        # ann_generate leaves the kernel name NULL (libhpnn.c:969-971 never
        # copies the conf name), so the dump prints glibc's "(null)"
        kernel, eff_seed = generate_kernel(
            conf.seed, conf.n_inputs, conf.hiddens, conf.n_outputs,
            name="(null)")
        # ann_generate writes the time()-derived seed back into the conf
        # (libhpnn.c:970 passes &_CONF.seed) so the training shuffle and
        # any conf dump reuse the SAME seed
        conf.seed = eff_seed
    else:
        if conf.f_kernel is None:
            nn_error("can't load kernel: no filename!\n")
            return None
        kernel = load_kernel(conf.f_kernel)
        if kernel is None:
            # exact reference string (libhpnn.c:862) -- the filename is
            # already in ann_load's own "Error opening kernel file:" line
            nn_error("FAILED to load the NN kernel!\n")
            return None
    # ann_kernel_allocate's memory accounting line (ann.c:197), printed on
    # both the generate and load paths
    nn_out(f"[CPU] ANN total allocation: {kernel.allocation_bytes} "
           "(bytes)\n")
    # _NN(load,conf)'s own accounting (libhpnn.c:872): sizeof(nn_def)=72
    # plus the strlen (no NUL -- STRDUP_REPORT, common.h:122-127) of every
    # duplicated string and 4 bytes per [hidden] entry
    def_bytes = 72 + len(conf.name or "") + 4 * len(conf.hiddens) \
        + len(conf.f_kernel or "") + len(conf.samples or "") \
        + len(conf.tests or "")
    nn_out(f"NN definition allocation: {def_bytes} (bytes)\n")
    return NNDef(conf=conf, kernel=kernel)


def _dtype_of(conf: NNConf):
    import jax.numpy as jnp

    return {"f64": jnp.float64, "f32": jnp.float32,
            "bf16": jnp.bfloat16}.get(conf.dtype, jnp.float64)


def _tile_request(conf: NNConf) -> int:
    """Batched-tile engine request: HPNN_TILE env (int or "auto") wins
    over the conf's ``[tile]`` / the CLI's ``--tile``.  0 = off (the
    per-sample engines), >0 = explicit group size, -1 = autotuned."""
    env = os.environ.get("HPNN_TILE")
    if env:
        if env.strip().lower() == "auto":
            return -1
        try:
            return max(0, int(env))
        except ValueError:
            nn_warn(f"HPNN_TILE={env!r} is not an integer or 'auto'; "
                    "tile engine off\n")
            return 0
    return conf.tile


def _tile_storage_env() -> str | None:
    """HPNN_TILE_STORAGE, validated: bf16/f32/f64 pass through,
    anything else warns and is ignored -- the same lenient contract as
    ``_tile_request``'s HPNN_TILE handling (a bad env knob must not
    abort a training run with a traceback from deep inside the
    kernel)."""
    env = os.environ.get("HPNN_TILE_STORAGE")
    if not env:
        return None
    v = env.strip().lower()
    if v in ("bf16", "f32", "f64"):
        return v
    nn_warn(f"HPNN_TILE_STORAGE={env!r} is not bf16/f32/f64; legacy "
            "storage used\n")
    return None


def _resolve_tile(conf: NNConf, weights, dtype, kind: str,
                  momentum: bool) -> tuple[int, str | None, str | None]:
    """Concrete (tile, storage, route) for a non-zero tile request:
    explicit values pass through (route auto-resolved downstream),
    ``auto`` asks the measured autotuner (ops.autotune; heuristic
    default when autotuning is off) and its route decision is APPLIED,
    not just logged.  ``HPNN_TILE_STORAGE`` is an operator override on
    BOTH branches -- when set it beats the autotuner's storage choice."""
    req = _tile_request(conf)
    env_storage = _tile_storage_env()
    if req > 0:
        return req, env_storage, None
    from .ops import autotune

    dec = autotune.decide_tile([tuple(w.shape) for w in weights], dtype,
                               kind, momentum)
    storage = env_storage if env_storage is not None else dec["storage"]
    nn_dbg(f"autotune: tile={dec['tile']} route={dec['route']} "
           f"storage={storage}"
           + (" (HPNN_TILE_STORAGE override)"
              if env_storage is not None and env_storage != dec["storage"]
              else "")
           + f" ({dec['source']})\n")
    return int(dec["tile"]), storage, dec["route"]


def _shuffle_order(conf: NNConf, n: int, rng=None) -> list[int]:
    """Seeded shuffle of n files (libhpnn.c:1218-1229); seed 0 -> time()
    written back into the conf, as the reference mutates _CONF.seed.
    A persistent ``rng`` (multi-epoch training, NNDef.shuffle_rng)
    continues its stream instead of re-seeding."""
    if rng is not None:
        return shuffled_indices(rng, n)
    if conf.seed == 0:
        conf.seed = int(time.time())
    return shuffled_indices(GlibcRandom(conf.seed), n)


# bulk loading in shuffle order lives in io.corpus (parallel loader +
# packed corpus cache); it owns the driver's skip/diagnostic semantics
# that used to live here as _load_ordered, byte-for-byte.

# test-dir prefetch started by the last train_kernel call (tests join it
# to assert the pack landed; production never waits on it)
_prefetch_thread = None

# per-process epoch-staging accounting, read by scripts/epoch_bench.py:
# h2d_bytes/stage_s accumulate over epochs (stage = host work between the
# seeded shuffle and the training launch: listing, corpus load/gather,
# device upload dispatch); shuffle_s isolates the glibc shuffle, which is
# a byte-parity obligation identical in every mode; setup_* record the
# pipeline's one-time corpus residency cost.  The opt_state_* pair
# (ISSUE 12) reports the MEASURED per-device footprint of the sharded
# update state (BPM momentum + bf16-route f32 masters) next to what full
# replication would cost; dp_devices is the data-axis width it was
# measured over.
EPOCH_METRICS = {"epochs": 0, "h2d_bytes": 0, "stage_s": 0.0,
                 "shuffle_s": 0.0, "setup_h2d_bytes": 0, "setup_s": 0.0,
                 "mode": None, "opt_state_bytes_per_device": 0,
                 "opt_state_replicated_bytes": 0, "dp_devices": 1,
                 "tp_devices": 1, "weight_bytes_per_device": 0}


def reset_epoch_metrics() -> None:
    EPOCH_METRICS.update(epochs=0, h2d_bytes=0, stage_s=0.0, shuffle_s=0.0,
                         setup_h2d_bytes=0, setup_s=0.0, mode=None,
                         opt_state_bytes_per_device=0,
                         opt_state_replicated_bytes=0, dp_devices=1,
                         tp_devices=1, weight_bytes_per_device=0)


# --------------------------------------------------------------------------
# Device-slice pinning (ISSUE 19).  A training run normally sees the whole
# process device list (bounded by the HPNN_DP_DEVICES / HPNN_TP_DEVICES env
# knobs); the multi-job placement scheduler instead pins each concurrent job
# to a DISJOINT slice of that list.  The slice is thread-local -- each job
# worker thread wraps its ``train_job`` run in ``device_slice(devices)`` and
# every mesh/device decision below (``_dp_device_count``, the epoch
# pipeline's DP/TP branches, ``_clamped_model_mesh``, the restage DP
# trainers, train/cg.py) consults ``slice_devices()`` first.  An explicit
# slice WINS over the env knobs: the knobs bound the default
# (whole-process) slice only, so a pinned 4-device job on an 8-device host
# trains byte-identically to a serial run under ``HPNN_DP_DEVICES=4``.

import contextlib as _contextlib
import threading as _threading

_DEVICE_SLICE = _threading.local()


def slice_devices() -> list | None:
    """This thread's pinned device slice, or None (whole process)."""
    return getattr(_DEVICE_SLICE, "devices", None)


@_contextlib.contextmanager
def device_slice(devices):
    """Pin every mesh/device decision on THIS thread to ``devices``.

    Nest-safe (the previous slice is restored) and a no-op for a
    falsy device list.  Also makes ``devices[0]`` the thread's JAX
    default device so unsharded intermediates of a 1-device job land
    on its own slice instead of device 0.
    """
    if not devices:
        yield
        return
    import jax

    prev = getattr(_DEVICE_SLICE, "devices", None)
    _DEVICE_SLICE.devices = list(devices)
    try:
        with jax.default_device(devices[0]):
            yield
    finally:
        _DEVICE_SLICE.devices = prev


def _visible_device_count() -> int:
    """``jax.device_count()`` bounded by the thread's pinned slice."""
    sl = slice_devices()
    if sl is not None:
        return len(sl)
    import jax

    return jax.device_count()


def _dp_device_count() -> int:
    """Device count for the [batch] DP routes: every visible device,
    capped by ``HPNN_DP_DEVICES`` (operators pinning a run to a mesh
    slice; tests comparing the sharded trajectory against the
    single-device one in the same process).  On the pure-DP routes the
    cap IS the data-axis width; on the hybrid [model]+[batch] route it
    caps the WHOLE (data x model) grid -- the model axis keeps its
    share, so ``HPNN_DP_DEVICES=4`` with ``[model] 2`` yields a 2x2
    grid, not a 4x2 one.  A thread-local ``device_slice`` pin wins
    outright: the slice length IS the grid, env knobs untouched."""
    sl = slice_devices()
    if sl is not None:
        return len(sl)
    import jax

    from .utils.env import env_device_cap

    return env_device_cap("HPNN_DP_DEVICES", jax.device_count())


def _dp_slot_map(s: int, bsz: int, n_batches: int, bsz_pad: int):
    """Epoch-invariant [batch] slot geometry, the ONE source for both
    the restage staging scratch and the resident pipeline (the
    resident==restage byte-parity guarantee rides on the two routes
    agreeing): real row i lands at flat slot (i//bsz)*bsz_pad + i%bsz,
    every other slot is a masked pad.  Returns (pos, mask) with mask
    (n_batches, bsz_pad) float64 of 1.0 on real slots."""
    pos = (np.arange(s) // bsz) * bsz_pad + np.arange(s) % bsz
    mask = np.zeros((n_batches, bsz_pad), np.float64)
    mask.reshape(-1)[pos] = 1.0
    return pos, mask


def _dp_banner_lines(s: int, bsz: int, n_batches: int, bsz_pad: int,
                     n_data: int, unsharded: bool) -> list[str]:
    """[batch] minibatch-route console banners -- like ``_dp_slot_map``,
    the ONE source for the restage and resident paths (the strings are
    a resident==restage byte-parity surface).  The hybrid-mesh banner
    (``_hybrid_banner``) is prepended by BOTH routes when [model] rides
    along (ISSUE 17: the pipeline takes the hybrid route too)."""
    lines = []
    if unsharded:
        lines.append("DP: one device visible; minibatch training runs "
                     "unsharded\n")
    padded_rows = n_batches * bsz_pad - s
    if padded_rows:
        lines.append(f"DP: padding {padded_rows} masked row(s) "
                     f"(S={s}, batch={bsz} -> {bsz_pad} over {n_data} "
                     "data-shard(s))\n")
    return lines


def _hybrid_banner(n_data: int, n_model: int) -> str:
    """[batch]x[model] hybrid-mesh banner, shared restage/resident
    (parity surface)."""
    return (f"DP: hybrid mesh {n_data}x{n_model} "
            "(batch rows over data, weight rows over model)\n")


def _hybrid_model_axis(shards: int, ndev: int):
    """``(n_model, warn_text_or_None)`` for [model] riding a [batch]
    run: the largest divisor of the FULL device grid not exceeding the
    request (stricter than ``_clamped_model_mesh``'s cap-at-ndev: the
    hybrid mesh is a full ndev grid, so the model axis must divide it;
    the TP route's 1xN mesh can use a device subset instead).  Shared
    by the restage route and the epoch pipeline so the clamp warnings
    stay byte-identical."""
    if shards <= 1:
        return 1, None
    if ndev == 1:
        return 1, f"[model] {shards} > 1 visible device(s); using 1\n"
    n_model = min(shards, ndev)
    while ndev % n_model:
        n_model -= 1
    if n_model != shards:
        return n_model, (f"[model] {shards} clamped to {n_model} "
                         f"(device count {ndev})\n")
    return n_model, None


def _dp_tiled_banner(group: int, pad_to: int, meshed: bool,
                     storage) -> str:
    """[batch]+[tile] engine banner, shared restage/resident (parity
    surface)."""
    eff = -(-group // pad_to) * pad_to
    return ("DP: batched-tile convergence engine (group=" + str(group)
            + (f" -> {eff} over {pad_to} data-shard(s)" if eff != group
               else "")
            + (f", mesh={pad_to}" if meshed else "")
            + (f", storage={storage}" if storage else "") + ")\n")


class _EpochPipeline:
    """Device-resident multi-epoch training state (ISSUE 5 tentpole).

    Built once per multi-epoch run (``ckpt.trainer`` drives it through
    ``train_kernel``): the packed corpus is uploaded to device ONCE, the
    master weights live on device across epochs (donated from launch to
    launch on accelerators), and every epoch's host work shrinks to the
    glibc shuffle (byte-parity obligation), an int32 permutation upload
    -- O(4*n_samples) bytes instead of O(corpus bytes) -- and an
    on-device ``take`` gather.  Stats readback + console-line rendering
    run on the shared ``io_pool``, overlapped with the next epoch's
    device work; the trainer joins only at snapshot/exit boundaries,
    where :meth:`join` also syncs the float64 host weights the
    checkpoint manager and ``kernel.opt`` dump read.

    Corpora larger than the device budget (``HPNN_EPOCH_DEVICE_BUDGET_MB``,
    or a forced ``HPNN_EPOCH_SHARD_ROWS``) switch to sharded mode: the
    shuffled epoch is cut into row shards, each host-gathered from the
    listing-order pack and uploaded on the io_pool while the previous
    shard trains -- double-buffered H2D under the busy device, weights
    still carried on device launch to launch.

    ``[batch] B`` runs (ISSUE 12) ride the DP variant of the same
    contract: the corpus lives sharded ``P("data", None)`` over the
    data mesh, each epoch's shuffle becomes an int32 slot map consumed
    by an on-device gather + batch reshape, and the update state (BPM
    momentum; the f32 masters under [dtype] bf16) is carried
    1/N-sharded cross-replica (``parallel.dp``, Xu et al.
    arXiv:2004.13336) with its per-device bytes MEASURED into
    ``EPOCH_METRICS`` every epoch.

    Byte parity: the trajectory is bit-identical to the restaging path
    (gather-then-cast == cast-then-gather; the wdtype device carry
    round-trips through float64 losslessly; sharded update state is a
    value-preserving relayout), and the console stream is
    byte-identical at the grammar levels (-vv) -- deferred segments are
    replayed in order, pre-rendered with the verbosity snapshotted at
    format time.  ``HPNN_NO_EPOCH_PIPELINE=1`` is the escape hatch.
    """

    def __init__(self, rc, dtype, wdtype, shard_rows: int,
                 dp: str | None = None, mesh=None, n_model: int = 1,
                 tp: bool = False, tp_warn: str | None = None):
        self.rc = rc                      # ResidentCorpus (listing order)
        self.dtype = dtype
        self.wdtype = wdtype
        self.shard_rows = shard_rows
        self.dp = dp                      # None | "sgd" | "tiled"
        self.mesh = mesh                  # data/(data x model)/model mesh
        self.n_model = n_model            # model-axis width (hybrid route)
        self.tp = tp                      # pure [model] per-sample route
        self.tp_warn = tp_warn            # per-epoch clamp warning text
        self._tp_orig = None              # unpadded row dims (TP carry)
        if tp:
            self.mode = "tp-resident"
        elif dp:
            self.mode = "dp-tiled-resident" if dp == "tiled" \
                else ("dp-tp-resident" if n_model > 1 else "dp-resident")
        else:
            self.mode = "sharded" if shard_rows else "resident"
        self.weights = None               # device carry across epochs
        self.shapes = None                # static weight shapes (DP carry)
        self.x_dev = None
        self.t_dev = None
        self.train_fn = None
        self._dp_state = None             # lazy per-run DP epoch geometry
        # deferred console segments, strictly ordered: ("out", text)
        # literals (the trainer's EPOCH banners) and Futures resolving
        # to (rendered_stdout, epoch_summary)
        self.pending: list = []
        self.h2d_last = 0                 # bytes uploaded by the last epoch
        self.stage_last = 0.0             # host staging seconds, last epoch

    # --- construction -----------------------------------------------------

    @classmethod
    def build(cls, nn, conf):
        """Resident pipeline for this run, or None when the corpus is
        missing/empty or has non-replayable diagnostics (the caller
        stays on the per-epoch restaging path).

        ``[batch] B`` runs (ISSUE 12) build the DP variant: the corpus
        is uploaded ONCE sharded ``P("data", None)`` over the data mesh
        (rows zero-padded to the axis -- never gathered), and every
        epoch becomes an on-device permutation-gather feeding the
        minibatch engine (``dp == "sgd"``) or the batched-tile
        convergence engine (``dp == "tiled"``, a [tile] request).  The
        host-streaming shard mode stays single-device machinery: a
        [batch] corpus over the per-device budget restages instead.
        """
        import jax.numpy as jnp

        from .obs import trace as obs_trace
        from .utils.env import env_int

        import jax

        names = list_sample_dir(conf.samples)
        if not names:
            return None
        t0 = time.perf_counter()
        procs = jax.process_count()
        with obs_trace.span("corpus_load", samples=conf.samples,
                            files=len(names)):
            # multi-process: keep the rows pack-backed (memmap) so the
            # per-rank shard feeds below touch only this host's row
            # range -- no rank materializes the full corpus
            rc = corpus_io.load_resident(conf.samples, names,
                                         nn.kernel.n_inputs,
                                         nn.kernel.n_outputs,
                                         prefer_mmap=procs > 1)
        if rc is None or rc.n_rows == 0:
            return None
        dtype = _dtype_of(conf)
        wdtype = jnp.float32 if dtype == jnp.bfloat16 else dtype
        itemsize = jnp.dtype(dtype).itemsize
        row_bytes = (rc.X.shape[1] + rc.T.shape[1]) * itemsize
        dp = None
        mesh = None
        n_data = 1
        n_model = 1
        tp = False
        tp_warn = None
        shards = _model_shards(conf)
        if conf.batch > 0:
            dp = "tiled" if _tile_request(conf) else "sgd"
            if dp == "tiled" and shards > 1:
                # [tile]+[model] keeps the restage route (it warns and
                # falls back to minibatch DP there); the pipeline would
                # have to duplicate that fallback's console stream
                return None
            ndev = _dp_device_count()
            if shards > 1:
                n_model, tp_warn = _hybrid_model_axis(shards, ndev)
            if ndev > 1:
                from .parallel import make_mesh

                mesh = make_mesh(n_data=ndev // n_model, n_model=n_model,
                                 devices=slice_devices())
                n_data = ndev // n_model
        elif shards > 1:
            # pure [model]: the per-sample TP route rides the pipeline on
            # a 1xN model mesh (even N==1 after clamping -- the engine is
            # the same, which keeps kill/--resume byte-exact)
            from .parallel import make_mesh

            tp = True
            ndev = _visible_device_count()
            k = min(shards, ndev)
            if shards > ndev:
                # _clamped_model_mesh's exact warning, re-emitted per
                # epoch (the restage route warns every epoch)
                tp_warn = (f"[model] {shards} > {ndev} visible "
                           f"device(s); using {ndev}\n")
            mesh = make_mesh(n_data=1, n_model=k, devices=slice_devices())
            n_model = k
        shard_rows = 0
        if os.environ.get("HPNN_EPOCH_SHARD_ROWS"):
            # a SET knob suppresses the budget check entirely (the
            # pre-consolidation contract: out-of-range/malformed values
            # force the full-resident upload, they do not re-arm it)
            env = env_int("HPNN_EPOCH_SHARD_ROWS", 0)
            if 0 < env < rc.n_rows:
                shard_rows = env
        else:
            budget = env_int("HPNN_EPOCH_DEVICE_BUDGET_MB", 4096,
                             lo=0) << 20
            if budget and rc.n_rows * row_bytes // n_data > budget:
                # two shards live at once (double buffering)
                shard_rows = max(1, budget // row_bytes // 2)
        if shard_rows and (dp or tp):
            nn_dbg("epoch pipeline: [batch]/[model] corpus over the "
                   "per-device budget (host-stream sharding is "
                   "single-device machinery); restaging\n")
            return None
        pipe = cls(rc, dtype, wdtype, shard_rows, dp=dp, mesh=mesh,
                   n_model=n_model, tp=tp, tp_warn=tp_warn)
        if not shard_rows:
            # the ONE corpus upload of the whole run (cast once on the
            # way up -- elementwise, so identical to per-epoch casting).
            # The pure-TP route keeps plain resident arrays: its epoch
            # places replicated chunks itself (tp_train_epoch_resident).
            if mesh is not None and dp:
                import jax

                from .parallel.mesh import batch_sharding

                # rows zero-padded to the data axis so the sharding
                # divides; the permutation indexes real rows only, so
                # the padding is never gathered
                pad = (-rc.n_rows) % n_data
                total = rc.n_rows + pad
                bs = batch_sharding(mesh)
                if procs > 1:
                    # ISSUE 18: each rank feeds ONLY the row ranges its
                    # addressable devices own, sliced straight out of
                    # the pack memmap -- the corpus uploads once per
                    # host per run and no host ever holds a full copy
                    def _shard_feed(which):
                        def cb(idx):
                            rows = idx[0]
                            lo = rows.start or 0
                            hi = total if rows.stop is None \
                                else rows.stop
                            block = rc.padded_row_block(which, lo, hi,
                                                        total)
                            # cast exactly like the restage stager
                            # (elementwise, so gather/cast order and
                            # block boundaries cannot change bytes)
                            return np.asarray(
                                jnp.asarray(block, dtype=dtype))
                        return cb

                    pipe.x_dev = jax.make_array_from_callback(
                        (total, rc.X.shape[1]), bs, _shard_feed("x"))
                    pipe.t_dev = jax.make_array_from_callback(
                        (total, rc.T.shape[1]), bs, _shard_feed("t"))
                else:
                    X, T = rc.X, rc.T
                    if pad:
                        X = np.concatenate(
                            [X, np.zeros((pad, X.shape[1]), X.dtype)])
                        T = np.concatenate(
                            [T, np.zeros((pad, T.shape[1]), T.dtype)])
                    pipe.x_dev = jax.device_put(
                        jnp.asarray(X, dtype=dtype), bs)
                    pipe.t_dev = jax.device_put(
                        jnp.asarray(T, dtype=dtype), bs)
            else:
                pipe.x_dev = jnp.asarray(rc.X, dtype=dtype)
                pipe.t_dev = jnp.asarray(rc.T, dtype=dtype)
            if procs > 1:
                # count THIS host's upload, not the global array size
                EPOCH_METRICS["setup_h2d_bytes"] += sum(
                    sh.data.nbytes
                    for arr in (pipe.x_dev, pipe.t_dev)
                    for sh in arr.addressable_shards)
            else:
                EPOCH_METRICS["setup_h2d_bytes"] += (pipe.x_dev.nbytes
                                                     + pipe.t_dev.nbytes)
            # nothing reads the host rows again on this route (events
            # come from names/status) -- drop the float64 copy instead
            # of keeping ~2x the corpus in RSS for the whole run
            rc.release_rows()
        EPOCH_METRICS["setup_s"] += time.perf_counter() - t0
        EPOCH_METRICS["dp_devices"] = n_data
        EPOCH_METRICS["tp_devices"] = n_model
        nn_dbg(f"epoch pipeline: {pipe.mode}, {rc.n_rows} row(s)"
               + (f", shard={shard_rows}" if shard_rows else "")
               + (f", mesh={n_data}x{n_model}" if mesh is not None
                  else "") + "\n")
        return pipe

    # --- per-epoch --------------------------------------------------------

    def run_epoch(self, nn, sel, kind: str, momentum: bool):
        """Dispatch one epoch's device work from the resident corpus and
        queue its stats readback + line rendering on the io_pool."""
        import jax.numpy as jnp

        from . import ops

        if self.tp:
            return self._run_epoch_tp(nn, sel, kind, momentum)
        if self.dp == "sgd":
            return self._run_epoch_dp_sgd(nn, sel, kind, momentum)
        if self.dp == "tiled":
            return self._run_epoch_dp_tiled(nn, sel, kind, momentum)
        t0 = time.perf_counter()
        if self.train_fn is None:
            if _tile_request(nn.conf):
                # the batched-tile engine rides the pipeline unchanged:
                # same epoch-fn contract, donated carry, lazy stats
                tile, tstorage, troute = _resolve_tile(
                    nn.conf, nn.kernel.weights, self.dtype, kind, momentum)
                self.train_fn, _ = ops.select_train_epoch(
                    self.dtype, donate=True, defer_stats=True,
                    tile=tile, storage=tstorage, route=troute)
            else:
                self.train_fn, _ = ops.select_train_epoch(
                    self.dtype, donate=True, defer_stats=True, kind=kind)
        if self.weights is None:
            # first epoch (or post-resume) staging from the float64 host
            # weights; afterwards the carry never leaves the device
            self.weights = tuple(jnp.asarray(w, dtype=self.wdtype)
                                 for w in nn.kernel.weights)
            EPOCH_METRICS["setup_h2d_bytes"] += sum(
                w.nbytes for w in self.weights)
        from .obs import trace as obs_trace

        if self.shard_rows:
            self.stage_last = time.perf_counter() - t0  # grown per shard
            new_w, stats = self._sharded_epoch(sel, kind, momentum)
        else:
            with obs_trace.span("corpus_gather", rows=int(sel.size)):
                sel_dev = jnp.asarray(sel)  # THE per-epoch H2D: int32 perm
                xs = jnp.take(self.x_dev, sel_dev, axis=0)
                ts = jnp.take(self.t_dev, sel_dev, axis=0)
            self.h2d_last = sel.nbytes
            self.stage_last = time.perf_counter() - t0
            with obs_trace.span("device_launch", rows=int(sel.size),
                                mode=self.mode):
                new_w, stats = self.train_fn(self.weights, xs, ts, kind,
                                             momentum, alpha=0.2)
        self.weights = new_w
        fut = corpus_io.io_pool().submit(
            _render_training_lines, self.events_last, stats, kind,
            momentum, nn_log.get_verbosity())
        self.pending.append(fut)
        nn.last_epoch_stats = None        # real after join()
        return stats

    # --- [model] TP epochs (ISSUE 17) -------------------------------------

    def _run_epoch_tp(self, nn, sel, kind: str, momentum: bool):
        """One per-sample TP epoch on the row-sharded resident carry:
        the padded weight blocks stay on the model mesh across epochs,
        only the int32 permutation crosses the host boundary
        (``tp_train_epoch_resident``)."""
        import jax.numpy as jnp

        from .obs import trace as obs_trace
        from .parallel import (per_device_bytes, tp_resident_carry,
                               tp_train_epoch_resident)

        t0 = time.perf_counter()
        if self.tp_warn:
            # the restage route warns every epoch, AFTER that epoch's
            # banner -- ride the deferred queue to keep stream order
            self.pending.append(("entries", [("warn", self.tp_warn)]))
        if self.weights is None:
            staged = tuple(jnp.asarray(w, dtype=self.wdtype)
                           for w in nn.kernel.weights)
            self.weights, self._tp_orig = tp_resident_carry(staged,
                                                            self.mesh)
            EPOCH_METRICS["setup_h2d_bytes"] += sum(
                w.nbytes for w in staged)
            EPOCH_METRICS["weight_bytes_per_device"] = \
                per_device_bytes(self.weights)
        with obs_trace.span("corpus_gather", rows=int(sel.size)):
            sel_dev = jnp.asarray(sel)  # THE per-epoch H2D: int32 perm
            xs = jnp.take(self.x_dev, sel_dev, axis=0)
            ts = jnp.take(self.t_dev, sel_dev, axis=0)
        self.h2d_last = sel.nbytes
        self.stage_last = time.perf_counter() - t0
        with obs_trace.span("device_launch", rows=int(sel.size),
                            mode=self.mode):
            new_w, stats = tp_train_epoch_resident(
                self.weights, xs, ts, kind, momentum, self.mesh,
                donate=True, alpha=0.2)
        self.weights = new_w
        fut = corpus_io.io_pool().submit(
            _render_training_lines, self.events_last, stats, kind,
            momentum, nn_log.get_verbosity())
        self.pending.append(fut)
        nn.last_epoch_stats = None        # real after join()
        return stats

    # --- [batch] DP epochs (ISSUE 12) -------------------------------------

    def _dp_setup(self, nn, kind: str, momentum: bool):
        """Lazy per-run DP epoch geometry: batch shapes, the
        epoch-invariant mask and slot map, banner lines, the resident
        weight carry layout.  Mirrors ``_train_kernel_dp``'s per-epoch
        derivations exactly so the console stream stays byte-identical
        to the restaging route."""
        import jax.numpy as jnp

        from . import ops
        from .parallel.dp import dp_resident_carry
        from .parallel.mesh import DATA_AXIS

        conf = nn.conf
        s = self.rc.n_rows
        bsz = min(conf.batch, s)
        n_batches = -(-s // bsz)
        n_data = self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
        bsz_pad = -(-bsz // n_data) * n_data if self.mesh is not None \
            else bsz
        banners = _dp_banner_lines(s, bsz, n_batches, bsz_pad, n_data,
                                   unsharded=self.mesh is None)
        if self.n_model > 1:
            banners = [_hybrid_banner(n_data, self.n_model)] + banners
        pos, mask = _dp_slot_map(s, bsz, n_batches, bsz_pad)
        import jax

        if jax.process_count() > 1:
            # multi-process inputs must be global arrays; stage the mask
            # exactly like the restage route does (P(None, "data"))
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .parallel.mesh import global_array

            mb_dev = global_array(
                np.asarray(jnp.asarray(mask, dtype=self.dtype)),
                NamedSharding(self.mesh, P(None, DATA_AXIS)))
        else:
            mb_dev = jnp.asarray(mask, dtype=self.dtype)
        lr = ops.bpm_learn_rate(kind) if momentum \
            else ops.bp_learn_rate(kind)
        # the flat 1/N master-vector trick is a pure-DP layout; on a
        # hybrid mesh the TP engine carries f32 master row BLOCKS
        # instead.  Cross-process it would also strand the export on a
        # non-addressable flat vector -- masters stay replicated there.
        shard_master = (self.dtype == jnp.bfloat16
                        and self.mesh is not None and self.n_model == 1
                        and jax.process_count() == 1)
        self.shapes = tuple(tuple(int(d) for d in w.shape)
                            for w in nn.kernel.weights)
        if self.weights is None:
            staged = tuple(jnp.asarray(w, dtype=self.wdtype)
                           for w in nn.kernel.weights)
            if self.n_model > 1:
                from .parallel import tp_dp_resident_carry

                self.weights = tp_dp_resident_carry(staged, self.mesh)
            else:
                self.weights = dp_resident_carry(staged, self.mesh,
                                                 shard_master)
            EPOCH_METRICS["setup_h2d_bytes"] += sum(
                int(np.prod(sh)) for sh in self.shapes) \
                * jnp.dtype(self.wdtype).itemsize
        self._dp_state = {"s": s, "bsz": bsz, "n_batches": n_batches,
                          "bsz_pad": bsz_pad, "n_data": n_data,
                          "pos": pos, "mb_dev": mb_dev, "lr": lr,
                          "banners": banners,
                          "shard_master": shard_master}
        return self._dp_state

    def _run_epoch_dp_sgd(self, nn, sel, kind: str, momentum: bool):
        """One zero-restage minibatch DP epoch: host work is the int32
        slot map only; gather, batch reshape, scan and the 1/N-sharded
        update state all live on device (``dp_train_epoch_resident``)."""
        import jax.numpy as jnp

        from .obs import trace as obs_trace
        from .parallel.dp import dp_train_epoch_resident
        from .parallel.mesh import per_device_bytes

        t0 = time.perf_counter()
        if self._dp_state is None:
            self._dp_setup(nn, kind, momentum)
        st = self._dp_state
        if self.tp_warn:
            # per-epoch clamp warning, deferred for stream order (the
            # restage route warns before each epoch's banner lines)
            self.pending.append(("entries", [("warn", self.tp_warn)]))
        for text in st["banners"]:
            self.pending.append(("out", text))
        # THE per-epoch H2D: the permutation scattered into batch slots
        flat = np.zeros(st["n_batches"] * st["bsz_pad"], np.int32)
        flat[st["pos"]] = sel
        import jax

        if jax.process_count() > 1:
            # every rank computed the SAME slot map (the glibc shuffle
            # is replicated by RNG-state construction, asserted by the
            # crc32 agreement gate in _train_kernel_pipelined) -- stage
            # it as a replicated global array
            from .parallel.mesh import global_array, replicated

            sel_dev = global_array(flat, replicated(self.mesh))
        else:
            sel_dev = jnp.asarray(flat)
        self.h2d_last = flat.nbytes
        self.stage_last = time.perf_counter() - t0
        with obs_trace.span("device_launch", rows=int(sel.size),
                            mode=self.mode, n_data=st["n_data"]):
            if self.n_model > 1:
                from .parallel import tp_dp_train_epoch_resident

                new_w, dw, errs = tp_dp_train_epoch_resident(
                    self.weights, self.x_dev, self.t_dev, sel_dev,
                    st["mb_dev"], kind, momentum, st["lr"], alpha=0.2,
                    mesh=self.mesh, donate=True)
            else:
                new_w, dw, errs = dp_train_epoch_resident(
                    self.weights, self.x_dev, self.t_dev, sel_dev,
                    st["mb_dev"], kind, momentum, st["lr"], alpha=0.2,
                    mesh=self.mesh, shard_master=st["shard_master"],
                    shapes=self.shapes, donate=True)
        self.weights = new_w
        # measured (not by-construction) optimizer-state footprint
        state_arrays, n_state = [], 0
        if dw is not None:
            state_arrays += list(dw) if isinstance(dw, tuple) else [dw]
            n_state += 1
        if st["shard_master"]:
            state_arrays.append(new_w)
            n_state += 1
        params = sum(int(np.prod(sh)) for sh in self.shapes)
        itemsize = jnp.dtype(self.wdtype).itemsize
        EPOCH_METRICS["opt_state_bytes_per_device"] = \
            per_device_bytes(state_arrays)
        EPOCH_METRICS["opt_state_replicated_bytes"] = \
            params * itemsize * n_state
        if self.n_model > 1:
            EPOCH_METRICS["weight_bytes_per_device"] = \
                per_device_bytes(new_w.blocks)
        fut = corpus_io.io_pool().submit(
            _render_dp_lines, errs, st["s"], nn_log.get_verbosity())
        self.pending.append(fut)
        nn.last_epoch_stats = None        # real after join()
        return errs

    def _run_epoch_dp_tiled(self, nn, sel, kind: str, momentum: bool):
        """One zero-restage [batch]+[tile] epoch: permutation-gather
        from the sharded resident rows, then the batched-tile
        convergence engine with lanes over the data axis and the
        momentum carry pinned cross-replica (``dp_tiled_epoch``)."""
        import jax.numpy as jnp

        from .obs import trace as obs_trace
        from .parallel.dp import dp_tiled_epoch

        t0 = time.perf_counter()
        if self._dp_state is None:
            self._dp_tiled_setup(nn, kind, momentum)
        st = self._dp_state
        if st["auto_warn"]:
            nn_warn("[tile] auto on the [batch] route: the group size IS "
                    "the minibatch and [tile] only sets launch "
                    "granularity (results identical for any value) -- "
                    "the autotuner does not apply; default launch "
                    "sizing used\n")
        self.pending.append(("out", st["banner"]))
        sel_dev = jnp.asarray(sel)
        self.h2d_last = sel.nbytes
        with obs_trace.span("corpus_gather", rows=int(sel.size)):
            xs = jnp.take(self.x_dev, sel_dev, axis=0)
            ts = jnp.take(self.t_dev, sel_dev, axis=0)
        self.stage_last = time.perf_counter() - t0
        with obs_trace.span("device_launch", rows=int(sel.size),
                            mode=self.mode):
            new_w, stats = dp_tiled_epoch(
                self.weights, xs, ts, kind, momentum, st["group"],
                alpha=0.2, mesh=self.mesh,
                launch_groups=st["launch_groups"],
                storage=st["storage"], donate=True)
        self.weights = tuple(new_w)
        fut = corpus_io.io_pool().submit(
            _render_training_lines, self.events_last, stats, kind,
            momentum, nn_log.get_verbosity())
        self.pending.append(fut)
        nn.last_epoch_stats = None
        return stats

    def _dp_tiled_setup(self, nn, kind: str, momentum: bool):
        """Lazy [batch]+[tile] geometry + the engine banner (identical
        strings to ``_train_kernel_dp_tiled``)."""
        import jax.numpy as jnp

        from .parallel.mesh import DATA_AXIS

        conf = nn.conf
        s = self.rc.n_rows
        group = min(conf.batch, s) if conf.batch > 0 else s
        req = _tile_request(conf)
        launch_groups = req if req > 0 else 0
        storage = _tile_storage_env()
        n_data = self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
        banner = _dp_tiled_banner(group, n_data,
                                  meshed=self.mesh is not None,
                                  storage=storage)
        self.shapes = tuple(tuple(int(d) for d in w.shape)
                            for w in nn.kernel.weights)
        if self.weights is None:
            self.weights = tuple(jnp.asarray(w, dtype=self.wdtype)
                                 for w in nn.kernel.weights)
            EPOCH_METRICS["setup_h2d_bytes"] += sum(
                w.nbytes for w in self.weights)
        self._dp_state = {"group": group, "launch_groups": launch_groups,
                          "storage": storage, "auto_warn": req < 0,
                          "n_data": n_data, "banner": banner}
        return self._dp_state

    def _sharded_epoch(self, sel, kind: str, momentum: bool):
        """Shuffled epoch over a corpus bigger than the device budget:
        row shards host-gathered from the listing-order pack and
        uploaded on the io_pool while the previous shard trains (weights
        carried on device shard to shard -- trajectory identical to one
        launch, the chunked_epoch argument)."""
        import jax.numpy as jnp

        from . import ops

        X, T, k = self.rc.X, self.rc.T, self.shard_rows
        n = int(sel.size)
        pool = corpus_io.io_pool()

        def prep(lo):
            idx = sel[lo:lo + k]
            return (jnp.asarray(X[idx], dtype=self.dtype),
                    jnp.asarray(T[idx], dtype=self.dtype))

        from .obs import trace as obs_trace

        w, parts, h2d = self.weights, [], 0
        nxt = pool.submit(prep, 0)
        for lo in range(0, n, k):
            t0 = time.perf_counter()
            xs, ts = nxt.result()
            if lo + k < n:
                nxt = pool.submit(prep, lo + k)
            h2d += xs.nbytes + ts.nbytes
            self.stage_last += time.perf_counter() - t0
            with obs_trace.span("device_launch", shard_lo=lo,
                                rows=int(xs.shape[0]), mode="sharded"):
                w, st = self.train_fn(w, xs, ts, kind, momentum,
                                      alpha=0.2)
            parts.append(st)
        self.h2d_last = h2d
        if len(parts) == 1:
            return w, parts[0]
        stats = ops.SampleStats(
            *(jnp.concatenate([getattr(p, f) for p in parts])
              for f in ops.SampleStats._fields))
        return w, stats

    # --- join (snapshot/exit boundaries) ----------------------------------

    def join(self, nn) -> list[dict]:
        """Drain the deferred console queue in order and sync the device
        weight carry back to ``nn.kernel.weights`` (float64, the form
        snapshots and kernel dumps read).  Returns the epoch summaries
        joined, oldest first."""
        from .obs import trace as obs_trace

        sums = []
        with obs_trace.span("stats_drain", pending=len(self.pending)):
            return self._join_inner(nn, sums)

    def _join_inner(self, nn, sums: list) -> list[dict]:
        for item in self.pending:
            if isinstance(item, tuple):
                tag, payload = item
                if tag == "out":
                    nn_out(payload)
                else:           # "entries": captured prologue output
                    nn_log.replay(payload)
            else:
                text, summary = item.result()
                nn_log.nn_raw(text)
                sums.append(summary)
                nn.last_epoch_stats = summary
        self.pending = []
        if self.weights is not None:
            if self.tp or (self.dp == "sgd" and self.n_model > 1):
                # TP carries live as padded row blocks on the model
                # mesh; export replicates once, unpads, and drops back
                # to the float64 host topology
                from .parallel import tp_export_weights

                if self.tp:
                    blocks, orig = self.weights, self._tp_orig
                else:
                    blocks, orig = self.weights.blocks, self.weights.orig
                nn.kernel.weights = [
                    np.asarray(w, dtype=np.float64)
                    for w in tp_export_weights(blocks, orig, self.mesh)]
            elif self.dp == "sgd":
                # the DP carry may live as the flat 1/N-sharded master
                # vector (bf16 route); export re-materializes layers
                from .parallel.dp import dp_export_weights

                nn.kernel.weights = dp_export_weights(self.weights,
                                                      self.shapes)
            else:
                nn.kernel.weights = [np.asarray(w, dtype=np.float64)
                                     for w in self.weights]
        return sums


def _pipeline_for(nn, conf):
    """The run's epoch pipeline: the existing one (latched -- the
    on/off decision is made once per run), or a fresh build when this
    run qualifies, else None (per-epoch restaging path)."""
    cur = getattr(nn, "_epoch_pipeline", None)
    if isinstance(cur, _EpochPipeline):
        return cur
    if cur is False:
        return None
    pipe = None
    if (nn.shuffle_rng is not None                    # multi-epoch driver
            and conf.train in (NN_TRAIN_BP, NN_TRAIN_BPM)
            and conf.samples is not None
            and not os.environ.get("HPNN_NO_EPOCH_PIPELINE")):
        from .utils.trace import trace_enabled

        import jax

        if not trace_enabled():
            if jax.process_count() == 1:
                pipe = _EpochPipeline.build(nn, conf)
            elif (conf.batch > 0 and _model_shards(conf) <= 1
                    and not _tile_request(conf)):
                # cross-host zero-restage (ISSUE 18): the pure-DP
                # [batch] route rides the pipeline across process
                # boundaries -- per-rank shard feeds, replicated slot
                # map.  Hybrid/[tile]/per-sample keep the restage route
                # (their engines are single-controller or warn there).
                pipe = _EpochPipeline.build(nn, conf)
    nn._epoch_pipeline = pipe if pipe is not None else False
    return pipe


def pipeline_active(nn) -> bool:
    """True when ``nn`` trains through the device-resident pipeline."""
    return isinstance(getattr(nn, "_epoch_pipeline", None), _EpochPipeline)


def pipeline_defer_out(nn, text: str) -> bool:
    """Queue an NN_OUT line behind the pipeline's deferred epochs (the
    trainer's EPOCH banner must follow the previous epoch's per-sample
    lines).  Returns False when no pipeline is active -- the caller
    prints normally."""
    pipe = getattr(nn, "_epoch_pipeline", None)
    if not isinstance(pipe, _EpochPipeline):
        return False
    pipe.pending.append(("out", text))
    return True


def pipeline_join(nn) -> list[dict]:
    """Drain the pipeline at a snapshot/exit boundary; no-op ([]) when
    no pipeline is active."""
    pipe = getattr(nn, "_epoch_pipeline", None)
    if not isinstance(pipe, _EpochPipeline):
        return []
    return pipe.join(nn)


def _train_kernel_pipelined(nn, pipe: _EpochPipeline, kind: str,
                            momentum: bool) -> bool:
    """One epoch through the device-resident pipeline: shuffle ->
    events + int32 permutation -> on-device gather -> donated training
    launch; emission deferred to the io_pool.  Console side effects
    (skip diagnostics on stderr, the LNN warnings, the grammar lines)
    land byte-identically to the restaging path at the -vv parity
    surface."""
    import jax

    from .parallel.coord import agree_all
    from .utils.trace import phase

    conf = nn.conf
    t0 = time.perf_counter()
    order = _shuffle_order(conf, len(pipe.rc.names), nn.shuffle_rng)
    EPOCH_METRICS["shuffle_s"] += time.perf_counter() - t0
    t1 = time.perf_counter()
    # shuffle-order header events + skip diagnostics (stderr), exactly
    # what the per-epoch load replays
    events, sel = pipe.rc.epoch_events(order)
    events_s = time.perf_counter() - t1
    pipe.events_last = events
    # the replicated-shuffle assertion (ISSUE 18): every rank's glibc
    # stream must have produced the SAME epoch permutation -- a crc32 of
    # the gather indices rides the existing agreement gate, so a
    # diverged RNG state aborts loudly instead of training on silently
    # different slot maps
    import zlib

    if not agree_all(True, (int(sel.size), nn.kernel.n_inputs,
                            nn.kernel.n_outputs,
                            zlib.crc32(np.ascontiguousarray(sel)
                                       .tobytes()))):
        return False
    # test-dir prefetch, exactly like the restaging epoch
    global _prefetch_thread
    _prefetch_thread = None
    if conf.tests and jax.process_count() == 1:
        _prefetch_thread = corpus_io.prefetch_pack_async(
            conf.tests, nn.kernel.n_inputs, nn.kernel.n_outputs)
    pipe.stage_last = 0.0
    with phase("train_epoch"):
        pipe.run_epoch(nn, sel, kind, momentum)
    EPOCH_METRICS["stage_s"] += events_s + pipe.stage_last
    EPOCH_METRICS["h2d_bytes"] += pipe.h2d_last
    EPOCH_METRICS["epochs"] += 1
    EPOCH_METRICS["mode"] = pipe.mode
    # the reference tail (libhpnn.c:1291-1301); native LNN (kind) skips
    # the unimplemented warning like ANN/SNN
    if conf.type in (NN_TYPE_ANN, NN_TYPE_SNN) or kind == NN_TYPE_LNN:
        if momentum:
            nn.kernel.momentum_free()
    else:
        nn_error("unimplemented NN type!\n")
    if not getattr(nn, "_pipeline_defer", False):
        # standalone callers (no trainer driving the join points) get
        # their output and host weights back at every epoch boundary --
        # still device-resident between calls, just not deferred
        pipe.join(nn)
    return True


def kernel_kind(conf: NNConf) -> str:
    """The compute family a conf's model actually trains/evals with.

    The reference routes LNN through the SNN code paths after warning
    (``libhpnn.c:1260-1261``) -- the default here, byte-for-byte.  With
    the native LNN opt-in (``[lnn] native`` / ``--lnn native`` /
    ``HPNN_LNN_NATIVE=1``) the linear-output regression head
    (ops.steps) takes over instead."""
    if conf.type == NN_TYPE_ANN:
        return NN_TYPE_ANN
    from .train import native_lnn

    if native_lnn(conf):
        return NN_TYPE_LNN
    return NN_TYPE_SNN


def train_kernel(nn: NNDef) -> bool:
    """_NN(train,kernel) (``libhpnn.c:1149-1305``): seeded shuffle, per-sample
    train-to-convergence, per-sample console line -- one on-device epoch."""
    import jax.numpy as jnp

    from . import ops

    conf = nn.conf
    if nn.kernel is None or conf.samples is None:
        return False
    if conf.type == NN_TYPE_UKN:
        return False
    momentum = conf.train == NN_TRAIN_BPM
    from .train import native_lnn, native_trainer

    lnn_native = native_lnn(conf)

    def _prologue():
        if conf.type in (NN_TYPE_ANN, NN_TYPE_SNN) or lnn_native:
            if momentum:
                # ann_momentum_init (libhpnn.c:1175)
                nn.kernel.momentum_init()
        else:
            # LNN: the reference warns here but does NOT return --
            # training proceeds through the SNN fallthrough
            # (libhpnn.c:1180-1182, 1260-1261).  (LNN+BPM would
            # dereference NULL momentum there; we train with zeroed
            # momentum instead -- documented deviation.)  The native
            # linear-output opt-in (kernel_kind) silences this.
            nn_error("unimplemented NN type!\n")

    if pipeline_active(nn) and getattr(nn, "_pipeline_defer", False):
        # deferred epochs: the prologue's stdout (MOMENTUM ALLOC) must
        # queue BEHIND the previous epoch's deferred lines; its stderr
        # (the LNN warning) emits now, like every other stderr byte
        with nn_log.capture() as pro:
            _prologue()
        err = [e for e in pro if e[0] == "error"]
        rest = [e for e in pro if e[0] != "error"]
        nn_log.replay(err)
        if rest:
            nn._epoch_pipeline.pending.append(("entries", rest))
    else:
        _prologue()

    from .utils.trace import phase, trace_weights

    dtype = _dtype_of(conf)
    # [dtype] bf16 keeps f32 MASTER weights on every training route
    # (samples/activations stay bf16): pure-bf16 weight storage loses
    # any update below a weight's bf16 ULP -- measured on the XRD BPM
    # cycle as <1% of weights ever moving.  The Pallas kernel computes
    # bf16 on the MXU against the f32 master; the XLA routes (DP/TP/
    # non-TPU) promote the mixed bf16 x f32 matmuls to f32 -- mixed
    # precision either way, never a silent training freeze.
    wdtype = jnp.float32 if dtype == jnp.bfloat16 else dtype
    nn.last_epoch_stats = None

    # device-resident epoch pipeline (multi-epoch runs): corpus uploaded
    # once per run -- sharded over the data mesh on the [batch] DP route
    # (ISSUE 12) -- per-epoch H2D shrinks to the int32 permutation,
    # weights carried on device epoch to epoch
    pipe = _pipeline_for(nn, conf)
    if pipe is not None:
        return _train_kernel_pipelined(nn, pipe, kernel_kind(conf),
                                       momentum)

    names = list_sample_dir(conf.samples)
    staged = None
    if names is not None:
        t_sh = time.perf_counter()
        order = _shuffle_order(conf, len(names), nn.shuffle_rng)
        EPOCH_METRICS["shuffle_s"] += time.perf_counter() - t_sh
        t_stage = time.perf_counter()
        # ingestion overlap: the corpus loads on background threads
        # (pack-cache fast path, else parallel per-file reads) while
        # this thread warms the device route -- H2D of the master
        # weights and the epoch implementation selection (first jax /
        # Pallas imports) run during the file walk instead of after it
        handle = corpus_io.load_ordered_async(
            conf.samples, names, order, "TRAINING",
            nn.kernel.n_inputs, nn.kernel.n_outputs)
        with phase("warmup"):
            staged = tuple(jnp.asarray(w, dtype=wdtype)
                           for w in nn.kernel.weights)
            if conf.batch <= 0 and _model_shards(conf) <= 1:
                ops.select_train_epoch(dtype, kind=kernel_kind(conf))
        with phase("load_samples"):
            events, xs, ts = handle.result()
        EPOCH_METRICS["stage_s"] += time.perf_counter() - t_stage
    else:
        events, xs, ts = [], None, None
    # multi-process agreement gate BEFORE any return path: a rank whose
    # sample dir is missing/divergent must drag every other rank out of
    # the upcoming collective instead of leaving them blocked in it
    # (ann.c:242-248 bailout, extended to data loading).  Fingerprint =
    # (sample count, dims): all ranks must have loaded the SAME corpus.
    from .parallel.coord import agree_all

    if names is None:
        # the failing rank names its own cause BEFORE the collective gate
        nn_error(f"can't open sample directory: {conf.samples}\n")
    if not agree_all(names is not None,
                     (0 if xs is None else xs.shape[0],
                      nn.kernel.n_inputs, nn.kernel.n_outputs)):
        return False
    if names is None:
        return False
    def finish() -> bool:
        # the tail the reference always runs (libhpnn.c:1291-1301):
        # momentum teardown for ANN/SNN, second warning for LNN
        if conf.type in (NN_TYPE_ANN, NN_TYPE_SNN) or lnn_native:
            if momentum:
                nn.kernel.momentum_free()  # ann_momentum_free (libhpnn.c:1297)
        else:
            nn_error("unimplemented NN type!\n")
        return True

    # native trainer registry (hpnn_tpu.train): an opted-in entry (e.g.
    # --trainer cg on a [train] CG conf) takes the whole epoch here --
    # whole-corpus GEMM-shaped loss/grad, its own one-line-per-epoch
    # grammar.  Without the opt-in, [train] CG keeps the reference's
    # untrainable fallthrough below, byte-for-byte.
    entry = native_trainer(conf)
    if entry is not None and xs is not None:
        kind = kernel_kind(conf)
        weights = staged
        trace_weights(weights, "train-in")
        with phase(f"train_epoch_{entry.name}"):
            new_weights = entry.run_epoch(nn, weights, xs, ts, kind,
                                          wdtype)
            nn.kernel.weights = [np.asarray(w, dtype=np.float64)
                                 for w in new_weights]
        ok = finish()
        trace_weights(nn.kernel.weights, "train-out")
        return ok

    trainable = conf.train in (NN_TRAIN_BP, NN_TRAIN_BPM)
    if xs is None or not trainable:
        # CG/SPLX are declared but unimplemented (libhpnn.c:1253-1257): the
        # reference still prints each per-file header, runs nothing per
        # sample (res=0), and returns TRUE -- so every header line is left
        # unterminated, exactly like a skipped file.
        for line, _ in events:
            nn_out(line)
        return finish()

    # the warmup block staged the master weights during the corpus load
    # (names is not None on every path reaching here, so staged is set)
    weights = staged
    # LNN trains through the SNN fallthrough (libhpnn.c:1260-1261)
    # unless the native linear-output head is opted in (kernel_kind)
    kind = kernel_kind(conf)
    trace_weights(weights, "train-in")

    # prefetch the TEST corpus while the epoch runs on device: the host
    # is idle through the device phase, so the pack for conf.tests is
    # built in the background and the upcoming run_kernel (this process
    # or the tutorial's fresh run_nn) warm-loads it.  Single-process
    # only -- multi-host IO stays exactly as scheduled before.
    global _prefetch_thread
    _prefetch_thread = None
    import jax

    if conf.tests and jax.process_count() == 1:
        _prefetch_thread = corpus_io.prefetch_pack_async(
            conf.tests, nn.kernel.n_inputs, nn.kernel.n_outputs)

    model_shards = _model_shards(conf)
    if conf.batch > 0:
        # [batch] B extension: data-parallel minibatch training (new
        # capability, BASELINE.json config 5) -- batches split over the
        # mesh's data axis, gradient all-reduce compiled by XLA.  The
        # per-sample convergence grammar does not apply; one line per batch.
        # Interaction with [model]: HYBRID -- a (data x model) mesh,
        # batch rows over "data" AND weight rows over "model" (GSPMD
        # compiles the induced all-gathers + all-reduces together).
        #
        # Routing is SEMANTIC, not a performance fallback (VERDICT r3
        # missing 4, measured round 4): the XLA minibatch epoch runs ONE
        # update per sample per epoch at 51-129 TFLOPS f32 on-chip
        # (26-65% MFU; committed artifact DP_PROFILE.md, regenerate with
        # scripts/dp_profile.py --out DP_PROFILE.md), while the Pallas route
        # below runs the reference's per-sample train-TO-CONVERGENCE
        # loop (~500-2000 data-dependent iterations per sample at ~786k
        # iters/s).  The two are different training algorithms with
        # incomparable sample rates; fusing DP into the convergence
        # kernel would change neither, so [batch] stays on XLA -- batched
        # GEMMs are exactly what XLA tiles best.
        with phase("train_epoch_dp"):
            ok = _train_kernel_dp(nn, weights, xs, ts, kind, momentum,
                                  finish, model_shards, events)
    elif model_shards > 1:
        # [model] N / -S N: the reference's intra-layer row sharding
        # (its ONLY distributed strategy, ann.c:913-936 dispatched from
        # libhpnn.c:1243-1283), reachable from the production driver.
        with phase("train_epoch_tp"):
            ok = _train_kernel_tp(nn, weights, xs, ts, kind, momentum,
                                  events, finish, model_shards, dtype)
    else:
        # the Pallas VMEM-persistent kernel serves f32/bf16 on TPU, the
        # XLA path serves fp64 parity and other backends
        # (ops.select_train_epoch); --tile S opts into the batched-tile
        # engine (groups of S to convergence, GEMM-shaped -- documented
        # trajectory divergence for S>1, per-sample grammar unchanged)
        if _tile_request(conf):
            tile, tstorage, troute = _resolve_tile(conf, weights, dtype,
                                                   kind, momentum)
            train_epoch_fn, _ = ops.select_train_epoch(
                dtype, tile=tile, storage=tstorage, route=troute)
        else:
            train_epoch_fn, _ = ops.select_train_epoch(dtype, kind=kind)
        t_up = time.perf_counter()
        xs_dev = jnp.asarray(xs, dtype=dtype)
        ts_dev = jnp.asarray(ts, dtype=dtype)
        EPOCH_METRICS["stage_s"] += time.perf_counter() - t_up
        EPOCH_METRICS["h2d_bytes"] += (xs_dev.nbytes + ts_dev.nbytes
                                       + sum(w.nbytes for w in weights))
        EPOCH_METRICS["epochs"] += 1
        EPOCH_METRICS["mode"] = "restage"
        with phase("train_epoch"):
            new_weights, stats = train_epoch_fn(
                weights, xs_dev, ts_dev,
                kind, momentum, alpha=0.2)  # alpha=.2 (libhpnn.c:1248)
            nn.kernel.weights = [np.asarray(w, dtype=np.float64)
                                 for w in new_weights]
        nn.last_epoch_stats = _emit_training_lines(events, stats, kind,
                                                   momentum)
        ok = finish()
    trace_weights(nn.kernel.weights, "train-out")
    return ok


def _model_shards(conf: NNConf) -> int:
    """Row-sharding degree: [model] N wins; else the -S knob (the
    reference's streams-per-GPU row split, train_nn.c -S -> stream count
    feeding red=N/total_s, cuda_ann.cu:536-537)."""
    if conf.model > 0:
        return conf.model
    from . import runtime

    return runtime.lib_runtime.n_streams


def _render_training_lines(events, stats, kind: str, momentum: bool,
                           verbosity: int):
    """Vectorized reconstruction of the reference's per-sample console
    stream (grammar: ann.c:2322-2366, snn.c:1496-1499): one numpy pass
    formats every column of the scanned statistics, one join assembles
    the epoch's stdout block -- byte-identical to emitting the pieces
    through nn_out/nn_cout/nn_dbg one sample at a time, with the
    verbosity gates and prefixes applied at format time.  Below the
    NN_OUT level (verbosity <= 1) no string is materialized at all
    (the 60k-per-epoch ``"%s"`` formats the old loop always paid).
    Runs on io_pool workers for the epoch pipeline (the np.asarray
    calls are the overlapped stats D2H).  Returns (stdout_text,
    epoch_summary)."""
    final_dep = np.asarray(stats.final_dep, dtype=np.float64)
    success = np.asarray(stats.success)
    n = int(final_dep.shape[0])
    summary = {"samples": n,
               "mean_final": float(np.mean(final_dep)) if n else None,
               "success": int(np.sum(success)) if n else 0}
    if verbosity <= 1:
        return "", summary
    blocks: list[str] = []
    if n:
        init_err = np.asarray(stats.init_err, dtype=np.float64)
        first_ok = np.asarray(stats.first_ok)
        n_iter = np.asarray(stats.n_iter).astype(np.int64)
        snn_bp = kind == NN_TYPE_SNN and not momentum
        b = np.char.mod(" init=%15.10f", init_err)
        b = np.char.add(b, np.where(first_ok, " OK", " NO"))
        b = np.char.add(b, np.char.mod(" N_ITER=%8d", n_iter))
        b = np.char.add(b, np.char.mod(" final=%15.10f", final_dep))
        if snn_bp:
            # snn_train_BP ends without a verdict (snn.c:1496-1499)
            b = np.char.add(b, "\n")
        else:
            b = np.char.add(b, np.where(success, " SUCCESS!\n",
                                        " FAIL!\n"))
        if verbosity > 2:
            b = np.char.add(b, np.where(final_dep > 0.1,
                                        "NN(DBG): bad optimization!\n",
                                        ""))
        blocks = b.tolist()
    parts: list[str] = []
    for line, i in events:
        parts.append("NN: ")
        parts.append(line)
        # skipped file: header only, no newline (libhpnn.c:1242)
        if i is not None:
            parts.append(blocks[i])
    return "".join(parts), summary


def _render_dp_lines(errs, n_samples: int, verbosity: int):
    """Deferred rendering of the minibatch DP console stream (one line
    per batch, ``_train_kernel_dp``'s exact format) plus the epoch
    summary the checkpoint manifest records.  Runs on io_pool workers
    for the DP epoch pipeline -- the np.asarray is the overlapped errs
    D2H.  Returns (stdout_text, epoch_summary)."""
    errs = np.asarray(errs, dtype=np.float64)
    summary = {"samples": int(n_samples),
               "mean_final": float(np.mean(errs)) if errs.size else None,
               "success": 0}
    if verbosity <= 1:
        return "", summary
    text = "".join(f"NN: TRAINING BATCH {i:8d}\t err={e:15.10f}\n"
                   for i, e in enumerate(errs))
    return text, summary


def _emit_training_lines(events, stats, kind: str, momentum: bool) -> dict:
    """Render + emit the per-sample training stream; returns the epoch
    summary (mean final error, success count) the checkpoint manifest's
    error trajectory records."""
    text, summary = _render_training_lines(events, stats, kind, momentum,
                                           nn_log.get_verbosity())
    nn_log.nn_raw(text)
    return summary


def _clamped_model_mesh(shards: int):
    """(mesh, shards) for an N-way model axis, clamped to visible devices
    with a warning -- shared by the TP train and eval routes.  Honors a
    thread-local ``device_slice`` pin (the warning then counts the
    slice's devices, matching what the mesh is actually built over)."""
    from .parallel import make_mesh

    ndev = _visible_device_count()
    if shards > ndev:
        nn_warn(f"[model] {shards} > {ndev} visible device(s); "
                f"using {ndev}\n")
        shards = ndev
    return make_mesh(n_data=1, n_model=shards,
                     devices=slice_devices()), shards


def _train_kernel_tp(nn: NNDef, weights, xs, ts, kind: str, momentum: bool,
                     events, finish, shards: int, dtype) -> bool:
    """Tensor-parallel per-sample training ([model] N / -S N).

    Builds a model-axis mesh and runs the whole epoch through
    ``tp_train_epoch`` -- ONE jitted ``lax.scan`` over the sample axis:
    every sample's convergence while-loop runs SPMD with the weight rows
    sharded ``P('model', None)`` and XLA-inserted all-gathers per layer --
    the reference's strategy (``ann.c:913-936``), with zero-padding
    replacing its redundant remainder rows.  Weights stay resident on the
    mesh across the whole epoch.  Sequential sample order and every update
    rule are identical to the single-device path, so logs and final
    weights match it (ulp-level: sharded compilation may fuse
    differently).

    ``dtype`` is the CONF activation dtype: under [dtype] bf16 the
    weights arriving here are the f32 masters while xs/ts cast to bf16,
    so the matmuls run mixed bf16 x f32 exactly like the DP route
    (ADVICE r3: deriving the cast from weights[0].dtype silently ran the
    TP route in pure f32).
    """
    import jax.numpy as jnp

    from .parallel import tp_train_epoch

    mesh, shards = _clamped_model_mesh(shards)
    w, stats = tp_train_epoch(
        weights, jnp.asarray(xs, dtype=dtype), jnp.asarray(ts, dtype=dtype),
        kind, momentum, mesh, alpha=0.2)
    # events' row index i is assigned in load order, so the i-th loaded
    # row is the i-th entry of the scanned-out stats
    nn.last_epoch_stats = _emit_training_lines(events, stats, kind,
                                               momentum)
    nn.kernel.weights = [np.asarray(v, dtype=np.float64) for v in w]
    return finish()


# pooled DP staging scratch (ISSUE 12 satellite): the per-epoch
# pad+scatter reuses one set of host buffers per batch geometry instead
# of allocating (and zero-filling) n_batches * bsz_pad rows every epoch
# -- pad slots are zeroed once at allocation and only real slots are
# overwritten (jnp.asarray copies on dispatch, so reuse is safe).
# Bounded like the serve registry's per-bucket scratch pools.
_dp_scratch: dict = {}
_DP_SCRATCH_MAX = 4


def _dp_stage_batches(xs, ts, s: int, bsz: int, n_batches: int,
                      bsz_pad: int, np_dtype):
    """Vectorized [batch] host staging: one fancy-index scatter of the
    shuffled rows into pooled (n_batches, bsz_pad, n) scratch --
    replaces the per-batch Python copy loop that ran every epoch.
    Returns (xb, tb, mb) with pad slots zero and mask 1.0 on real
    slots, byte-identical to the old loop's output."""
    # the FULL batch geometry keys the pool: bsz changes the slot map
    # (pos) and mask even when (n_batches, bsz_pad, s) collide -- e.g.
    # 9 samples as 3 batches of 3 vs 3 batches of 4, both padded to 8
    key = (s, bsz, n_batches, bsz_pad, xs.shape[1], ts.shape[1],
           np.dtype(np_dtype).str)
    got = _dp_scratch.pop(key, None)
    if got is None:
        xb = np.zeros((n_batches, bsz_pad, xs.shape[1]), np_dtype)
        tb = np.zeros((n_batches, bsz_pad, ts.shape[1]), np_dtype)
        pos, mask = _dp_slot_map(s, bsz, n_batches, bsz_pad)
        mb = mask.astype(np_dtype)
        got = (xb, tb, mb, pos)
    xb, tb, mb, pos = got
    xb.reshape(-1, xs.shape[1])[pos] = xs
    tb.reshape(-1, ts.shape[1])[pos] = ts
    _dp_scratch[key] = got                # re-insertion refreshes LRU age
    while len(_dp_scratch) > _DP_SCRATCH_MAX:
        _dp_scratch.pop(next(iter(_dp_scratch)))
    return xb, tb, mb


def _train_kernel_dp(nn: NNDef, weights, xs, ts, kind: str, momentum: bool,
                     finish, model_shards: int = 1, events=None) -> bool:
    """Data-parallel minibatch epoch ([batch] B conf extension).

    With a tile request ([tile]/--tile/HPNN_TILE, ISSUE 6) the route
    swaps its engine: instead of one SGD step per batch, every
    [batch]-sized group trains TO CONVERGENCE in lockstep through the
    batched-tile kernel (``parallel.dp.dp_tiled_epoch`` -- lanes sharded
    over the mesh's data axis, per-lane masking), and the per-sample
    console grammar returns because ``SampleStats`` are exact again.

    Uses the reference's per-family learning rates and the BPM update
    order.  Every sample trains: batches are padded up to a multiple of
    the data-axis size with masked-out rows (numerically identical to the
    unpadded batch -- the mask zeroes deltas and the mean divides by the
    real count), instead of silently dropping the tail or falling back to
    one device.  Multi-process runs (HPNN_DISTRIBUTED) build global
    arrays: every process loads the shared-filesystem corpus and
    contributes the rows its devices own -- the reference's MPI layout
    (``libhpnn.c:1184-1229``) without the rank-0 Bcast hub.

    ``model_shards > 1`` ([model] N alongside [batch]) builds a HYBRID
    (data x model) mesh: batch rows over "data" AND weight rows over
    "model" (the reference's row layout, ann.c:913-926).  GSPMD compiles
    the per-layer all-gathers and the gradient all-reduce together; rows
    that do not divide the model axis stay replicated (layer_sharding --
    the output layer, typically).
    """
    import jax
    import jax.numpy as jnp

    from . import ops
    from .parallel import dp_train_epoch_batched, global_array, make_mesh
    from .parallel.mesh import DATA_AXIS, layer_sharding
    from .parallel.mesh import replicated as replicated_sharding

    conf = nn.conf
    if _tile_request(conf):
        if jax.process_count() > 1:
            # once per process, not per epoch: train_kernel re-enters
            # here every epoch of a multi-epoch run
            if not getattr(nn, "_tile_mp_warned", False):
                nn._tile_mp_warned = True
                nn_warn("[tile] engine is single-controller; "
                        "multi-process [batch] runs keep minibatch DP\n")
        elif model_shards > 1:
            nn_warn("[tile] + [model] hybrid is not supported; minibatch "
                    "DP keeps the hybrid mesh\n")
        else:
            return _train_kernel_dp_tiled(nn, weights, xs, ts, kind,
                                          momentum, finish, events)
    t_stage = time.perf_counter()
    lr = ops.bpm_learn_rate(kind) if momentum else ops.bp_learn_rate(kind)
    s = xs.shape[0]
    # (rank-divergence is handled by train_kernel's agreement gate, which
    # runs before EVERY return path and therefore before this collective)
    bsz = min(conf.batch, s)
    n_batches = -(-s // bsz)
    dtype = _dtype_of(conf)
    ndev = _dp_device_count()
    n_model, clamp_warn = _hybrid_model_axis(model_shards, ndev)
    if clamp_warn:
        nn_warn(clamp_warn)
    if ndev > 1:
        mesh = make_mesh(n_data=ndev // n_model, n_model=n_model,
                         devices=slice_devices())
    else:
        mesh = None
    if mesh is not None and n_model > 1:
        nn_out(_hybrid_banner(ndev // n_model, n_model))
    n_data = mesh.shape[DATA_AXIS] if mesh is not None else 1
    bsz_pad = -(-bsz // n_data) * n_data if mesh is not None else bsz
    for line in _dp_banner_lines(s, bsz, n_batches, bsz_pad, n_data,
                                 unsharded=mesh is None):
        nn_out(line)

    # bf16 stages through f32 HOST buffers only: both device paths re-cast
    # to the conf dtype (single-process jnp.asarray below; multi-process
    # host() before global_array), so the compute dtype is launch-mode
    # independent (ADVICE r3 checked exactly this)
    np_dtype = np.dtype(str(jnp.dtype(dtype))) if dtype != jnp.bfloat16 \
        else np.float32
    xb, tb, mb = _dp_stage_batches(xs, ts, s, bsz, n_batches, bsz_pad,
                                   np_dtype)

    def wsh(w):
        # ONE hybrid placement rule for both process layouts: rows over
        # "model" where they divide it, replicated otherwise
        return (layer_sharding(w, mesh) if n_model > 1
                else replicated_sharding(mesh))

    if jax.process_count() > 1 and mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        # host staging is f32/f64 numpy; the global arrays must carry the
        # CONF dtype (bf16 via ml_dtypes survives the numpy round-trip)
        def host(a):
            return np.asarray(jnp.asarray(a, dtype=dtype))

        bsh = NamedSharding(mesh, P(None, DATA_AXIS, None))
        msh = NamedSharding(mesh, P(None, DATA_AXIS))
        jxb = global_array(host(xb), bsh)
        jtb = global_array(host(tb), bsh)
        jmb = global_array(host(mb), msh)
        # weights keep their OWN dtype (the f32 master under [dtype]
        # bf16) -- host() would re-quantize them to the batch dtype
        weights = tuple(global_array(np.asarray(w), wsh(w))
                        for w in weights)
    else:
        jxb = jnp.asarray(xb, dtype=dtype)
        jtb = jnp.asarray(tb, dtype=dtype)
        jmb = jnp.asarray(mb, dtype=dtype)
        if mesh is not None:
            weights = tuple(jax.device_put(w, wsh(w)) for w in weights)
    EPOCH_METRICS["stage_s"] += time.perf_counter() - t_stage
    EPOCH_METRICS["h2d_bytes"] += (jxb.nbytes + jtb.nbytes + jmb.nbytes
                                   + sum(w.nbytes for w in weights))
    EPOCH_METRICS["epochs"] += 1
    EPOCH_METRICS["mode"] = "dp-restage"
    EPOCH_METRICS["dp_devices"] = n_data
    new_weights, errs = dp_train_epoch_batched(
        weights, jxb, jtb, jmb, kind, momentum, lr, alpha=0.2, mesh=mesh)
    if jax.process_count() > 1 and n_model > 1:
        # hybrid rows live as shards on other processes' devices; a host
        # fetch must gather them first (the reference's G2C staging step,
        # ann.c:808, in its DCN form)
        from jax.experimental import multihost_utils

        new_weights = multihost_utils.process_allgather(new_weights,
                                                        tiled=True)
    errs = np.asarray(errs, dtype=np.float64)
    for i in range(n_batches):
        nn_out(f"TRAINING BATCH {i:8d}\t err={errs[i]:15.10f}\n")
    nn.last_epoch_stats = {"samples": int(s),
                           "mean_final": float(np.mean(errs)),
                           "success": 0}
    nn.kernel.weights = [np.asarray(w, dtype=np.float64) for w in new_weights]
    return finish()


def _train_kernel_dp_tiled(nn: NNDef, weights, xs, ts, kind: str,
                           momentum: bool, finish, events) -> bool:
    """[batch] + [tile]: batched-tile convergence engine on the DP route
    (ISSUE 6 tentpole wiring).  The [batch] value is the convergence
    GROUP (the S lanes of each GEMM-shaped step); a positive [tile]
    value sets how many groups ride one device launch -- execution
    granularity only, SampleStats identical for ANY launch tiling
    (pinned in tests/test_tile_convergence.py).  Lane rows shard over
    the data mesh when more than one device is visible."""
    import jax.numpy as jnp

    from .parallel import make_mesh
    from .parallel.dp import dp_tiled_epoch

    conf = nn.conf
    dtype = _dtype_of(conf)
    s = xs.shape[0]
    group = min(conf.batch, s) if conf.batch > 0 else s
    req = _tile_request(conf)
    if req < 0:
        nn_warn("[tile] auto on the [batch] route: the group size IS "
                "the minibatch and [tile] only sets launch granularity "
                "(results identical for any value) -- the autotuner "
                "does not apply; default launch sizing used\n")
    launch_groups = req if req > 0 else 0
    storage = _tile_storage_env()
    ndev = _dp_device_count()
    mesh = (make_mesh(n_data=ndev, n_model=1, devices=slice_devices())
            if ndev > 1 else None)
    pad_to = mesh.shape["data"] if mesh is not None else 1
    nn_out(_dp_tiled_banner(group, pad_to, meshed=mesh is not None,
                            storage=storage))
    t_stage = time.perf_counter()
    xs_dev = jnp.asarray(xs, dtype=dtype)
    ts_dev = jnp.asarray(ts, dtype=dtype)
    EPOCH_METRICS["stage_s"] += time.perf_counter() - t_stage
    EPOCH_METRICS["h2d_bytes"] += (xs_dev.nbytes + ts_dev.nbytes
                                   + sum(w.nbytes for w in weights))
    EPOCH_METRICS["epochs"] += 1
    EPOCH_METRICS["mode"] = "dp-tiled-restage"
    EPOCH_METRICS["dp_devices"] = pad_to
    new_w, stats = dp_tiled_epoch(
        weights, xs_dev, ts_dev,
        kind, momentum, group, alpha=0.2, mesh=mesh,
        launch_groups=launch_groups, storage=storage)
    # per-sample grammar again: load order == stats order, exactly like
    # the sequential routes
    nn.last_epoch_stats = _emit_training_lines(events or [], stats, kind,
                                               momentum)
    nn.kernel.weights = [np.asarray(w, dtype=np.float64) for w in new_w]
    return finish()


def run_kernel(nn: NNDef) -> None:
    """_NN(run,kernel) (``libhpnn.c:1306-1536``): batched evaluation with the
    reference's PASS/FAIL stdout grammar."""
    import jax.numpy as jnp

    from . import ops

    conf = nn.conf
    from .utils.trace import phase

    # a rank-divergent conf (no kernel, no [test_dir], unknown type) must
    # still reach the agreement collective below, or the healthy peers
    # block in it forever -- so these "early returns" are deferred until
    # after the gate
    usable = (nn.kernel is not None and conf.tests is not None
              and conf.type != NN_TYPE_UKN)
    names, events, xs, ts = None, [], None, None
    weights = None
    xs_dev = None
    if usable:
        names = list_sample_dir(conf.tests)
        if names is not None:
            order = _shuffle_order(conf, len(names))
            # ingestion overlap: the test corpus loads in the background
            # (warm loads mmap the pack train_kernel prefetched) while
            # this thread stages the weights on device
            handle = corpus_io.load_ordered_async(
                conf.tests, names, order, "TESTING",
                nn.kernel.n_inputs, nn.kernel.n_outputs)
            with phase("warmup"):
                dtype = _dtype_of(conf)
                weights = tuple(jnp.asarray(w, dtype=dtype)
                                for w in nn.kernel.weights)
                ops.select_run_batch(dtype, kind=kernel_kind(conf))
            with phase("load_tests"):
                events, xs, ts = handle.result()
            if xs is not None:
                # stream the loaded rows to device ahead of the eval
                # launch: jax dispatch is async, so the H2D copy overlaps
                # the agreement gate and event bookkeeping below
                xs_dev = jnp.asarray(xs, dtype=dtype)
    # Coordinated eval bailout (the ann.c:242-248 handshake class, here
    # guarding the RUN path): one rank with a missing/divergent test dir
    # must abort EVERY rank before the sharded eval collective below, or
    # the peers block in it forever.  Same gate configure/train_kernel
    # already use (VERDICT r4 weak 2).  Every rank reaches this exact
    # call: the local-failure returns come AFTER the collective.
    from .parallel.coord import agree_all

    # fingerprint the LOADED row count (not len(names)): _load_ordered
    # silently skips unreadable/mismatched files, and a rank whose copy
    # of one test file is corrupt would otherwise agree on the listing
    # count and then enter the collective with a shorter batch
    ok = xs is not None
    fp = ((xs.shape[0], nn.kernel.n_inputs, nn.kernel.n_outputs)
          if ok else (0, 0, 0))
    agreed = agree_all(ok, fp)
    if not usable:
        return
    if names is None:
        nn_error(f"can't open test directory: {conf.tests}\n")
        return
    if xs is None:
        for line, _ in events:
            nn_out(line)
        return
    if not agreed:
        return

    # weights/xs_dev were staged during the overlapped load: every path
    # reaching the eval below had usable names + loaded rows
    dtype = _dtype_of(conf)
    # LNN evaluates through the SNN branch (libhpnn.c:1455-1456) unless
    # the native linear-output head is opted in (kernel_kind)
    kind = kernel_kind(conf)
    model_shards = _model_shards(conf)
    with phase("eval_batch"):
        if model_shards > 1:
            # [model] N / -S N: row-sharded evaluation -- the reference's
            # run path splits the same rows across ranks/streams
            # (libhpnn.c:1426 -> ann.c:913-936)
            from .parallel import tp_eval_batch

            mesh, _ = _clamped_model_mesh(model_shards)
            outs = np.asarray(tp_eval_batch(weights, xs_dev, kind, mesh),
                              dtype=np.float64)
        else:
            run_batch_fn, _ = ops.select_run_batch(dtype, kind=kind)
            outs = np.asarray(run_batch_fn(weights, xs_dev, kind),
                              dtype=np.float64)

    n_out = nn.kernel.n_outputs
    for line, i in events:
        nn_out(line)
        if i is None:
            continue
        out, t = outs[i], ts[i]
        if kind == NN_TYPE_ANN:
            # res=-1.; guess=n_outputs; is_ok=TRUE(=1)  (libhpnn.c:1443-1450)
            res = -1.0
            guess = n_out
            target = 1
            for idx in range(n_out):
                if res < out[idx]:
                    guess = idx
                    res = out[idx]
                if t[idx] > 0.5:
                    target = idx
            if guess == target:
                nn_cout(" [PASS]\n")
            else:
                nn_cout(f" [FAIL idx={target + 1}]\n")
        elif kind == NN_TYPE_LNN:
            # native LNN regression grammar (new capability -- the
            # reference has no LNN test path): per-output values at DBG,
            # one MSE summary per file.  No PASS/FAIL verdict: regression
            # has no class to match.
            nn_dbg("   IDX |          OUTPUT |          TARGET\n")
            nn_dbg("-------|-----------------|----------------\n")
            for idx in range(n_out):
                nn_dbg(f" {idx + 1:5d} | {out[idx]:15.10f} "
                       f"| {t[idx]:15.10f}\n")
            nn_dbg("-------|-----------------|----------------\n")
            mse = float(np.mean((out - t) ** 2))
            nn_cout(f" MSE={mse:15.10f}\n")
        else:
            # SNN: res=0; guess=0; is_ok=0  (libhpnn.c:1499-1514)
            res = 0.0
            guess = 0
            target = 0
            nn_dbg(" CLASS | PROBABILITY (%)\n")
            nn_dbg("-------|----------------\n")
            for idx in range(n_out):
                nn_dbg(f" {idx + 1:5d} | {out[idx] * 100.0:15.10f}\n")
                if out[idx] > res:
                    res = out[idx]
                    guess = idx
                if t[idx] > 0.1:
                    target = idx
            nn_dbg("-------|----------------\n")
            nn_cout(f" BEST CLASS idx={guess + 1} P={res * 100.0:15.10f}")
            if guess == target:
                nn_cout(" [PASS]\n")
            else:
                nn_cout(f" [FAIL idx={target + 1}]\n")


def train_job(conf_path: str, *, epochs: int, ckpt_dir: str,
              ckpt_every: int = 1, ckpt_keep: int = 0,
              kernel_out: str | None = None, resume: str | None = None,
              stop=None, on_epoch=None, replicate_to: str | None = None,
              auth_token: str | None = None, devices=None) -> dict:
    """Reentrant in-process training entry (the jobs subsystem's driver).

    The exact ``train_nn`` checkpoint path -- configure, multi-epoch
    ``ckpt.train_loop`` with crash-safe snapshots, final kernel dump +
    manifest stamp -- minus every process-global side effect the CLI
    owns: no runtime init/deinit, no cwd-relative ``kernel.tmp``/
    ``kernel.opt`` (the caller names ``kernel_out`` absolutely), no
    stderr writes, no signal handlers unless running on the main
    thread.  That is what makes it safe to call from a serve-process
    worker thread while eval traffic runs -- and what makes the parity
    contract literal: the same conf/corpus/seed produces a
    byte-identical kernel to the offline CLI (pinned in
    tests/test_jobs.py).

    ``resume`` names a checkpoint dir/bundle to continue bit-exactly
    (the ``--resume`` semantics: weights, BPM momentum, shuffle-RNG
    words and epoch counter restored).  ``stop``/``on_epoch`` pass
    through to :func:`ckpt.trainer.train_loop` (external cancel +
    epoch-boundary callback).

    ``devices`` pins the whole run -- configure, every epoch, the final
    dump -- to an explicit device slice via :func:`device_slice` (the
    placement scheduler's hook): mesh construction sees only the slice,
    so a 4-device pinned run is byte-identical to a serial run on any
    same-sized slice.  None keeps the whole-process view bounded by the
    env knobs.

    Returns ``{"ok", "interrupted", "epoch", "errors", "error"}`` --
    never raises for config/corpus problems (the scheduler maps the
    dict to a job status); checkpoint WRITER failures do raise, exactly
    like the CLI's flush-before-done contract.
    """
    with device_slice(devices):
        return _train_job_pinned(
            conf_path, epochs=epochs, ckpt_dir=ckpt_dir,
            ckpt_every=ckpt_every, ckpt_keep=ckpt_keep,
            kernel_out=kernel_out, resume=resume, stop=stop,
            on_epoch=on_epoch, replicate_to=replicate_to,
            auth_token=auth_token)


def _train_job_pinned(conf_path: str, *, epochs: int, ckpt_dir: str,
                      ckpt_every: int, ckpt_keep: int,
                      kernel_out: str | None, resume: str | None,
                      stop, on_epoch, replicate_to: str | None,
                      auth_token: str | None) -> dict:
    from .ckpt import CheckpointManager, load_snapshot, train_loop
    from .io.kernel_io import dump_kernel_to_path

    def fail(msg: str) -> dict:
        return {"ok": False, "interrupted": False, "epoch": 0,
                "errors": [], "error": msg}

    nn = configure(conf_path)
    if nn is None or nn.kernel is None:
        return fail(f"cannot read NN configuration {conf_path}")
    snap = None
    start_epoch = 0
    if resume:
        snap = load_snapshot(resume)
        if snap is None:
            return fail(f"no resumable snapshot at {resume}")
        if snap.topology != list(nn.kernel.params):
            return fail(f"snapshot topology {snap.topology} does not "
                        f"match the configured kernel "
                        f"{list(nn.kernel.params)}")
        nn.kernel.weights = list(snap.weights)
        nn.conf.seed = snap.seed
        start_epoch = snap.epoch
        # native-trainer carry (CG direction / prior gradient / restart
        # counter): restored like BPM momentum for bit-exact resume
        nn.trainer_state = snap.trainer_state
    mgr = CheckpointManager(ckpt_dir, every=ckpt_every,
                            keep_last=ckpt_keep, target_epochs=epochs,
                            replicate_to=replicate_to,
                            auth_token=auth_token)
    if snap is not None:
        mgr.seed_errors(snap.errors)
    if start_epoch >= epochs:
        # nothing left to train (e.g. resuming a job interrupted during
        # its final epoch): finalize exactly like a completed run -- the
        # CLI always dumps kernel.opt, and record_final's generation
        # bump is what tells watchers the run ended
        if kernel_out:
            dump_kernel_to_path(nn.kernel, kernel_out)
            mgr.record_final(kernel_out)
        else:
            mgr.flush()
        return {"ok": True, "interrupted": False, "epoch": start_epoch,
                "errors": list(mgr.errors), "error": None}
    trained, interrupted = train_loop(
        nn, epochs, manager=mgr, start_epoch=start_epoch,
        rng_state=snap.rng_state if snap is not None else None,
        stop=stop, on_epoch=on_epoch)
    if not trained:
        mgr.flush()
        return fail("training failed")
    if kernel_out:
        # interrupted runs dump too, exactly like the CLI: kernel_out
        # always holds the LAST trained state, and record_final's
        # generation bump is what tells watchers the run ended
        dump_kernel_to_path(nn.kernel, kernel_out)
        mgr.record_final(kernel_out)
    else:
        mgr.flush()
    return {"ok": True, "interrupted": bool(interrupted),
            "epoch": len(mgr.errors), "errors": list(mgr.errors),
            "error": None}


def dump_kernel_def(nn: NNDef, fp) -> bool:
    """_NN(dump,kernel) (libhpnn.c:996-1008)."""
    if nn.kernel is None:
        return False
    dump_kernel(nn.kernel, fp)
    return True
