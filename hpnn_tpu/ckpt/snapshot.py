"""On-disk snapshot format for the checkpoint subsystem.

A checkpoint directory holds a flat set of snapshot bundles plus one
manifest:

    <ckpt-dir>/
        manifest.json            latest tag, generation counter,
                                 fingerprints, error trajectory,
                                 retention policy, snapshot index
        ep00000003/              one bundle per checkpointed epoch
            kernel.opt           weights, reference text format
                                 (io.kernel_io -- loadable by run_nn,
                                 serve_nn and the compiled reference)
            state.npz            bit-exact training state: float64
                                 weights (w0..wN), BPM momentum buffers
                                 (m0..mN), the 33-word glibc shuffle-RNG
                                 state, epoch counter, effective seed
            snapshot.json        per-bundle manifest (tag, epoch, seed,
                                 fingerprint, mean error, topology)

Two weight encodings on purpose: the text format is the framework's
interop surface (``%17.15f`` quantizes -- fine for serving and for the
reference's own restart cycle), while ``state.npz`` carries the raw
float64 bits so ``train_nn --resume`` continues to a **byte-identical**
``kernel.opt`` versus the uninterrupted run (the repo's parity guarantee
extended across process death; pinned in tests/test_ckpt.py).

Crash safety: every bundle is staged under a dot-tmp directory, each
file fsync'd, then the DIRECTORY is renamed into place and the parent
fsync'd -- readers (the serve hot-reload watcher, a concurrent
``--resume``) see a complete bundle or none.  The manifest itself goes
through the shared ``io.atomic`` tmp+fsync+rename writer, and its
``generation`` counter increments on every publish, which is what the
serving registry's manifest watcher keys reloads on.

Verified writes + verified resume (ISSUE 14): every bundle file's
sha256 is recorded in ``snapshot.json`` (``fingerprints``), the staged
files are READ BACK and verified before the directory rename (bounded
retry with jittered backoff -- ``HPNN_CKPT_WRITE_RETRIES`` /
``HPNN_CKPT_RETRY_BACKOFF_S`` -- so a transient ENOSPC/EIO or a torn
write costs a retry, never a poisoned bundle), and the manifest is
only ever updated AFTER its bundle verified.  On resume the same
fingerprints are ENFORCED: :func:`load_snapshot` walks candidate
bundles newest-first, skipping any whose bytes no longer hash to what
``snapshot.json`` recorded (or that fail to parse at all) with a loud
``ckpt_fallback`` structured event -- training resumes from the newest
*intact* state instead of crashing on, or silently training from,
garbage.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import io
import json
import os
import shutil
import time

import numpy as np

from ..io.atomic import fsync_dir
from ..io.kernel_io import dumps_kernel, encode_kernel_text, load_kernel
from ..models.kernel import Kernel

MANIFEST = "manifest.json"
SNAPSHOT_META = "snapshot.json"
SNAPSHOT_STATE = "state.npz"
SNAPSHOT_KERNEL = "kernel.opt"
MANIFEST_VERSION = 1


def snapshot_tag(epoch: int) -> str:
    return f"ep{int(epoch):08d}"


def fingerprint_bytes(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def fingerprint_file(path: str) -> str | None:
    try:
        with open(path, "rb") as fp:
            return fingerprint_bytes(fp.read())
    except OSError:
        return None


@dataclasses.dataclass
class SnapshotState:
    """Everything ``train_nn --resume`` restores."""

    weights: list[np.ndarray]          # float64, bit-exact
    momentum: list[np.ndarray] | None  # BPM dw buffers (None for BP)
    rng_state: list[int] | None        # glibc shuffle stream (33 words)
    epoch: int
    seed: int
    errors: list[float]                # per-epoch mean final error
    tag: str
    path: str                          # bundle directory
    fingerprint: str | None            # of kernel.opt in the bundle
    target_epochs: int = 0             # the run's --epochs goal (0: unknown)
    # native-trainer carry (hpnn_tpu.train): flat f64 arrays keyed
    # cg_d/cg_g/cg_meta for the CG trainer (None for BP/BPM)
    trainer_state: dict | None = None
    # process count of the writing run (ISSUE 18): a resume at a
    # DIFFERENT world size is refused loudly -- the shuffle stream is
    # world-size independent but the run's collectives are not, and a
    # silent mismatch would diverge rank state.  1 for legacy bundles.
    world_size: int = 1

    @property
    def topology(self) -> list[int]:
        return [int(self.weights[0].shape[1]),
                *[int(w.shape[0]) for w in self.weights]]


def _durable_write(path: str, data: bytes) -> None:
    """Plain write + fsync (used INSIDE a staged tmp bundle, where the
    directory rename provides the atomicity).  Consults the chaos io
    domain like every durable writer (ISSUE 14) -- injected
    ENOSPC/EIO/torn/bitflip faults land HERE, below the bundle
    writer's verify-and-retry loop."""
    from ..io.atomic import io_fault_hook

    data = io_fault_hook(path, data)
    with open(path, "wb") as fp:
        fp.write(data)
        fp.flush()
        os.fsync(fp.fileno())


def _state_npz_bytes(weights, momentum, rng_state, epoch: int,
                     seed: int, trainer_state=None) -> bytes:
    arrays = {f"w{i}": np.asarray(w, dtype=np.float64)
              for i, w in enumerate(weights)}
    if momentum is not None:
        arrays.update({f"m{i}": np.asarray(m, dtype=np.float64)
                       for i, m in enumerate(momentum)})
    if rng_state is not None:
        arrays["rng"] = np.asarray(rng_state, dtype=np.int64)
    if trainer_state:
        # native-trainer carry (CG direction/grad/meta); keys are
        # namespaced "cg_*" so the momentum loader's "m"-prefix filter
        # and these never collide
        for k, v in trainer_state.items():
            if not k.startswith("cg_"):
                raise ValueError(f"trainer_state key {k!r} must be "
                                 "namespaced 'cg_*'")
            arrays[k] = np.asarray(v)
    arrays["meta"] = np.asarray([int(epoch), int(seed)], dtype=np.int64)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def write_retries() -> int:
    from ..utils.env import env_int

    return env_int("HPNN_CKPT_WRITE_RETRIES", 3, lo=0)


def _retry_backoff_s(attempt: int) -> float:
    """Jittered exponential backoff between bundle-write attempts."""
    import random

    from ..utils.env import env_float

    base = env_float("HPNN_CKPT_RETRY_BACKOFF_S", 0.05, lo=0.0)
    return base * (2.0 ** attempt) * (0.5 + random.random())


def _verify_staged(path: str, data: bytes) -> None:
    """Read a just-staged file back and compare against the intended
    payload: a torn or bit-flipped write is caught HERE, before the
    bundle rename can ever publish it (raises OSError to the retry
    loop)."""
    with open(path, "rb") as fp:
        if fp.read() != data:
            raise OSError(f"verify-after-write mismatch on {path}")


def write_snapshot(ckpt_dir: str, epoch: int, *, weights, momentum,
                   rng_state, seed: int, errors, name: str = "(null)",
                   train: str = "", dtype: str = "f64",
                   target_epochs: int = 0, trainer_state=None,
                   world_size: int = 1) -> dict:
    """Write one atomic bundle for ``epoch``; returns its index entry
    (tag/epoch/mean_err/fingerprint) for the manifest.  Every staged
    file is read back and byte-verified before the directory rename;
    a failed or corrupted write is retried (bounded, jittered backoff)
    and the LAST failure is raised -- a bundle either publishes
    verified or not at all.

    Runs on the io_pool writer thread in production -- it must not
    print (the caller owns the console stream's byte parity).
    """
    os.makedirs(ckpt_dir, exist_ok=True)
    tag = snapshot_tag(epoch)
    final = os.path.join(ckpt_dir, tag)
    tmp = os.path.join(ckpt_dir, f".tmp.{tag}.{os.getpid()}")
    kernel_text = dumps_kernel(Kernel(name=name, weights=list(weights)))
    kernel_bytes = encode_kernel_text(kernel_text)
    state_bytes = _state_npz_bytes(weights, momentum, rng_state, epoch,
                                   seed, trainer_state)
    fp_kernel = fingerprint_bytes(kernel_bytes)
    errors = [None if e is None else float(e) for e in errors]
    meta = {
        "tag": tag,
        "epoch": int(epoch),
        "seed": int(seed),
        "fingerprint": fp_kernel,
        "fingerprints": {SNAPSHOT_KERNEL: fp_kernel,
                         SNAPSHOT_STATE: fingerprint_bytes(state_bytes)},
        "mean_err": errors[-1] if errors else None,
        "errors": errors,
        "topology": [int(weights[0].shape[1]),
                     *[int(w.shape[0]) for w in weights]],
        "train": train,
        "dtype": dtype,
        "momentum": momentum is not None,
        "trainer_state": bool(trainer_state),
        "target_epochs": int(target_epochs),
        # the coherent-global-step stamp (ISSUE 18): how many processes
        # agreed (behind coord.snapshot_barrier) that this epoch is the
        # bundle -- resume refuses a different world size
        "world_size": int(world_size),
        "barrier_epoch": int(epoch) if int(world_size) > 1 else None,
        "created": time.time(),
    }
    meta_bytes = (json.dumps(meta, indent=1) + "\n").encode()
    files = ((SNAPSHOT_KERNEL, kernel_bytes),
             (SNAPSHOT_STATE, state_bytes),
             (SNAPSHOT_META, meta_bytes))
    last_exc: BaseException | None = None
    for attempt in range(write_retries() + 1):
        if attempt:
            time.sleep(_retry_backoff_s(attempt - 1))
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        try:
            os.makedirs(tmp)
            for fname, data in files:
                fpath = os.path.join(tmp, fname)
                _durable_write(fpath, data)
                _verify_staged(fpath, data)
            fsync_dir(tmp)
            if os.path.isdir(final):  # re-snapshot of the same epoch
                shutil.rmtree(final)
            os.replace(tmp, final)
        except OSError as exc:
            # transient disk trouble (ENOSPC burst, torn write): clean
            # the stage and retry -- nothing was ever renamed into
            # place, so no reader saw a partial bundle
            last_exc = exc
            with contextlib.suppress(OSError):
                shutil.rmtree(tmp)
            continue
        except BaseException:
            with contextlib.suppress(OSError):
                shutil.rmtree(tmp)
            raise
        fsync_dir(ckpt_dir)
        # the manifest entry carries EVERY file's fingerprint --
        # including snapshot.json's own, which cannot self-certify --
        # so verify_bundle has an external cross-check for each byte
        # of the bundle
        return {"tag": tag, "epoch": int(epoch),
                "mean_err": meta["mean_err"], "fingerprint": fp_kernel,
                "fingerprints": dict(
                    meta["fingerprints"],
                    **{SNAPSHOT_META: fingerprint_bytes(meta_bytes)})}
    raise OSError(f"CKPT: bundle {tag} failed verified write after "
                  f"{write_retries() + 1} attempt(s): {last_exc}")


# --- manifest ---------------------------------------------------------------

def manifest_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, MANIFEST)


def read_manifest(ckpt_dir: str) -> dict | None:
    """The checkpoint directory's manifest, or None when absent or
    unparseable (a half-created dir is not an error -- watchers poll)."""
    try:
        with open(manifest_path(ckpt_dir), "r") as fp:
            m = json.load(fp)
    except (OSError, ValueError, UnicodeDecodeError):
        # ValueError covers JSONDecodeError; UnicodeDecodeError covers
        # bit-rot that breaks the utf-8 stream itself
        return None
    return m if isinstance(m, dict) else None


def write_manifest(ckpt_dir: str, manifest: dict) -> None:
    """Verified manifest publish: tmp+fsync+rename via io.atomic, read
    back and compared, retried (bounded, jittered backoff) on any
    failure.  Because the replace is atomic and only runs after the
    temp file fsync'd, a failed attempt leaves the PREVIOUS manifest
    intact -- a disk fault can cost a generation bump, never a
    poisoned manifest."""
    manifest = dict(manifest)
    manifest["version"] = MANIFEST_VERSION
    manifest["updated"] = time.time()
    payload = (json.dumps(manifest, indent=1) + "\n").encode("utf-8")
    path = manifest_path(ckpt_dir)
    stage = f"{path}.stage.{os.getpid()}"
    last_exc: Exception | None = None
    for attempt in range(write_retries() + 1):
        if attempt:
            time.sleep(_retry_backoff_s(attempt - 1))
        try:
            # stage + verify FIRST, replace LAST: the previous
            # manifest must never be overwritten by bytes that have
            # not already been read back intact (a persistently
            # corrupting disk then exhausts the retries with the OLD
            # manifest still published)
            _durable_write(stage, payload)
            _verify_staged(stage, payload)
            os.replace(stage, path)
        except OSError as exc:
            last_exc = exc
            with contextlib.suppress(OSError):
                os.unlink(stage)
            continue
        fsync_dir(os.path.dirname(os.path.abspath(path)))
        return
    raise OSError(f"CKPT: manifest write failed after "
                  f"{write_retries() + 1} attempt(s): {last_exc}")


def publish_snapshot(ckpt_dir: str, entry: dict, *, seed: int, errors,
                     keep_last: int = 0) -> dict:
    """Fold one bundle's index entry into the manifest (generation bump)
    and apply retention.  Returns the manifest written."""
    prev = read_manifest(ckpt_dir) or {}
    snaps = [s for s in prev.get("snapshots", [])
             if s.get("tag") != entry["tag"]]
    snaps.append(entry)
    snaps.sort(key=lambda s: s.get("epoch", 0))
    manifest = dict(prev)
    manifest.update({
        "generation": int(prev.get("generation", 0)) + 1,
        "latest": entry["tag"],
        "epoch": entry["epoch"],
        "seed": int(seed),
        "fingerprint": entry["fingerprint"],
        "kernel": os.path.join(entry["tag"], SNAPSHOT_KERNEL),
        "errors": [None if e is None else float(e) for e in errors],
        "retention": {"keep_last": int(keep_last), "keep_best": True},
        "snapshots": snaps,
    })
    manifest["snapshots"] = _apply_retention(ckpt_dir, snaps, keep_last)
    write_manifest(ckpt_dir, manifest)
    return manifest


def record_final_kernel(ckpt_dir: str, kernel_path: str) -> None:
    """Stamp the manifest with the path + fingerprint of the final
    ``kernel.opt`` train_nn wrote, so ``run_nn`` (and ops tooling) can
    detect a stale or hand-edited weights file (generation bump: a
    watching server hot-reloads the finished kernel)."""
    fp = fingerprint_file(kernel_path)
    if fp is None:
        return
    manifest = read_manifest(ckpt_dir) or {}
    manifest["generation"] = int(manifest.get("generation", 0)) + 1
    manifest["final_kernel"] = os.path.abspath(kernel_path)
    manifest["final_fingerprint"] = fp
    write_manifest(ckpt_dir, manifest)


def refresh_final_kernel(ckpt_dir: str, kernel_path: str) -> None:
    """Keep the manifest honest across PLAIN (non-checkpointed)
    retrains: when a manifest already tracks exactly this kernel file,
    re-record its fingerprint after a fresh dump -- otherwise every
    later ``run_nn`` would warn 'stale or modified weights' about a
    kernel that is actually NEWER than the manifest, training users to
    ignore the guard.  A no-op when no manifest tracks the file."""
    manifest = read_manifest(ckpt_dir)
    if not manifest:
        return
    if manifest.get("final_kernel") == os.path.abspath(kernel_path):
        record_final_kernel(ckpt_dir, kernel_path)


def _apply_retention(ckpt_dir: str, snaps: list[dict],
                     keep_last: int) -> list[dict]:
    """keep-last-N + best-by-error: the N most recent bundles always
    survive, and so does the lowest-mean-error one (keep_last <= 0 keeps
    everything).  Pruned bundles are deleted from disk."""
    if keep_last <= 0 or len(snaps) <= keep_last:
        return snaps
    by_epoch = sorted(snaps, key=lambda s: s.get("epoch", 0))
    keep = {s["tag"] for s in by_epoch[-keep_last:]}
    scored = [s for s in snaps if s.get("mean_err") is not None]
    if scored:
        keep.add(min(scored, key=lambda s: s["mean_err"])["tag"])
    kept = []
    for s in by_epoch:
        if s["tag"] in keep:
            kept.append(s)
            continue
        with contextlib.suppress(OSError):
            shutil.rmtree(os.path.join(ckpt_dir, s["tag"]))
    return kept


# --- resume ----------------------------------------------------------------

def _bundle_tags(path: str) -> list[str]:
    """Bundle directory names under a checkpoint dir, newest epoch
    first (tags sort lexically == numerically by construction)."""
    try:
        return sorted((t for t in os.listdir(path)
                       if t.startswith("ep") and os.path.isfile(
                           os.path.join(path, t, SNAPSHOT_STATE))),
                      reverse=True)
    except OSError:
        return []


def candidate_bundles(path: str) -> list[str]:
    """Every bundle a ``--resume``/recovery of ``path`` could load,
    newest-first: an explicit bundle dir leads, then the manifest's
    latest, then every remaining on-disk bundle by descending epoch --
    the walk-back order for verified resume."""
    path = os.path.abspath(path)
    if os.path.isfile(path):
        path = os.path.dirname(path)
    if not os.path.isdir(path):
        return []
    out: list[str] = []
    if os.path.isfile(os.path.join(path, SNAPSHOT_STATE)):
        # an explicit bundle dir: it leads, its siblings are fallback
        out.append(path)
        path = os.path.dirname(path)
    manifest = read_manifest(path)
    if manifest and manifest.get("latest"):
        bundle = os.path.join(path, manifest["latest"])
        if os.path.isfile(os.path.join(bundle, SNAPSHOT_STATE)):
            out.append(bundle)
    out.extend(os.path.join(path, t) for t in _bundle_tags(path))
    seen: set[str] = set()
    return [b for b in out if not (b in seen or seen.add(b))]


def _manifest_fingerprints(bundle: str) -> dict:
    """The manifest's recorded per-file fingerprints for this bundle
    (empty when the manifest is absent/corrupt/legacy).  This is the
    EXTERNAL cross-check: ``snapshot.json`` cannot certify its own
    bytes, so its sha256 lives in the manifest entry."""
    manifest = read_manifest(os.path.dirname(os.path.abspath(bundle)))
    if not manifest:
        return {}
    tag = os.path.basename(bundle.rstrip(os.sep))
    for entry in manifest.get("snapshots", []):
        if isinstance(entry, dict) and entry.get("tag") == tag:
            prints = entry.get("fingerprints")
            return prints if isinstance(prints, dict) else {}
    return {}


def verify_bundle(bundle: str) -> tuple[bool, str]:
    """ENFORCE a bundle's recorded fingerprints (ISSUE 14): every file
    named in ``snapshot.json``'s ``fingerprints`` map -- plus the
    manifest entry's cross-check, which covers ``snapshot.json``
    itself -- must hash to its recorded sha256, and ``state.npz`` must
    structurally parse.  An unparseable ``snapshot.json`` is corrupt
    (bundles publish atomically; a half file cannot exist).  Legacy
    bundles (no ``fingerprints``) fall back to the kernel-only
    ``fingerprint`` field plus the parse check.  Returns
    ``(ok, reason)`` -- reason names the first failing file."""
    meta = None
    with contextlib.suppress(OSError, ValueError, UnicodeDecodeError):
        with open(os.path.join(bundle, SNAPSHOT_META)) as fp:
            meta = json.load(fp)
    if not isinstance(meta, dict):
        return False, f"{SNAPSHOT_META}: missing or unparseable"
    prints = dict(_manifest_fingerprints(bundle))
    own = meta.get("fingerprints")
    if isinstance(own, dict):
        # the bundle's own map fills anything the manifest lacks; on
        # conflict the manifest wins (it is the external witness)
        for k, v in own.items():
            prints.setdefault(k, v)
    elif not prints and meta.get("fingerprint"):
        prints[SNAPSHOT_KERNEL] = meta["fingerprint"]
    for fname, recorded in sorted(prints.items()):
        actual = fingerprint_file(os.path.join(bundle, fname))
        if actual is None:
            return False, f"{fname}: unreadable"
        if actual != recorded:
            return False, f"{fname}: sha256 mismatch"
    try:
        with np.load(os.path.join(bundle, SNAPSHOT_STATE),
                     allow_pickle=False) as z:
            if "meta" not in z.files:
                return False, f"{SNAPSHOT_STATE}: missing meta"
    except (OSError, KeyError, ValueError) as exc:
        return False, f"{SNAPSHOT_STATE}: {type(exc).__name__}: {exc}"
    return True, "ok"


def _load_bundle_state(bundle: str) -> SnapshotState | None:
    from ..utils.nn_log import nn_error

    try:
        with np.load(os.path.join(bundle, SNAPSHOT_STATE),
                     allow_pickle=False) as z:
            weights = [z[k] for k in sorted(
                (k for k in z.files if k.startswith("w")),
                key=lambda k: int(k[1:]))]
            momentum = [z[k] for k in sorted(
                (k for k in z.files if k.startswith("m") and k != "meta"),
                key=lambda k: int(k[1:]))] or None
            rng = [int(v) for v in z["rng"]] if "rng" in z.files else None
            trainer_state = {k: z[k] for k in z.files
                             if k.startswith("cg_")} or None
            epoch, seed = (int(v) for v in z["meta"])
    except (OSError, KeyError, ValueError) as exc:
        nn_error(f"CKPT: unreadable snapshot state in {bundle}: {exc}\n")
        return None
    meta = {}
    with contextlib.suppress(OSError, ValueError, UnicodeDecodeError):
        with open(os.path.join(bundle, SNAPSHOT_META)) as fp:
            meta = json.load(fp)
    errors = [e for e in meta.get("errors", [])]
    fp_actual = fingerprint_file(os.path.join(bundle, SNAPSHOT_KERNEL))
    return SnapshotState(weights=weights, momentum=momentum,
                         rng_state=rng, epoch=epoch, seed=seed,
                         errors=errors, tag=os.path.basename(bundle),
                         path=bundle, fingerprint=fp_actual,
                         target_epochs=int(meta.get("target_epochs", 0)),
                         trainer_state=trainer_state,
                         world_size=int(meta.get("world_size", 1)))


def load_snapshot(path: str, verify: bool = True) -> SnapshotState | None:
    """Load a bundle (or a checkpoint dir's latest bundle) back into
    host state.  Weights come from ``state.npz`` -- bit-exact float64,
    NOT the quantized text -- which is what makes resume byte-identical.

    Verified resume with last-good fallback (ISSUE 14): candidates are
    tried newest-first; a bundle whose bytes no longer match its
    recorded fingerprints (or fail to parse) is SKIPPED with a loud
    ``ckpt_fallback`` structured event + NN(WARN), and the walk
    continues to the newest intact bundle -- resume never crashes on,
    or silently trains from, a corrupted snapshot.  Returns None (with
    an NN(ERR) diagnostic) when nothing intact is found."""
    from ..utils.nn_log import nn_error, nn_event, nn_warn

    candidates = candidate_bundles(path)
    if not candidates:
        nn_error(f"CKPT: no resumable snapshot at {path}\n")
        return None
    for bundle in candidates:
        if verify:
            ok, reason = verify_bundle(bundle)
            if not ok:
                nn_warn(f"CKPT: snapshot {bundle} failed verification "
                        f"({reason}); falling back to the previous "
                        "intact bundle\n")
                nn_event("ckpt_fallback", bundle=bundle, reason=reason)
                continue
        snap = _load_bundle_state(bundle)
        if snap is not None:
            return snap
    nn_error(f"CKPT: no INTACT snapshot at {path} "
             f"({len(candidates)} candidate(s) all failed "
             "verification)\n")
    return None


def looks_like_checkpoint(path: str) -> bool:
    """Is ``path`` plausibly a checkpoint dir/bundle/file?  The CLI's
    ``--resume [PATH]`` grammar uses this to tell an optional resume
    path from the trailing conf filename."""
    if os.path.isdir(path):
        return (os.path.isfile(os.path.join(path, MANIFEST))
                or os.path.isfile(os.path.join(path, SNAPSHOT_STATE))
                or any(t.startswith("ep") for t in os.listdir(path)))
    return os.path.basename(path) in (MANIFEST, SNAPSHOT_META,
                                      SNAPSHOT_STATE)


def check_kernel_fingerprint(kernel_path: str | None,
                             ckpt_dir: str) -> bool:
    """``run_nn`` guard (satellite): when the checkpoint manifest has a
    recorded fingerprint for this exact kernel file and the bytes on
    disk no longer match, WARN with both paths instead of silently
    evaluating stale/modified weights.  Returns False on mismatch."""
    from ..utils.nn_log import nn_warn

    if not kernel_path:
        return True
    manifest = read_manifest(ckpt_dir)
    if not manifest:
        return True
    kp = os.path.abspath(kernel_path)
    recorded = None
    if manifest.get("final_kernel") == kp:
        recorded = manifest.get("final_fingerprint")
    elif manifest.get("kernel") and os.path.join(
            os.path.abspath(ckpt_dir), manifest["kernel"]) == kp:
        recorded = manifest.get("fingerprint")
    if not recorded:
        return True
    actual = fingerprint_file(kp)
    if actual is None or actual == recorded:
        return True
    nn_warn(f"kernel fingerprint mismatch: {kp} does not match the "
            f"manifest {manifest_path(os.path.abspath(ckpt_dir))} "
            "(stale or modified weights?)\n")
    return False


def load_bundle_kernel(bundle: str):
    """The bundle's text-format kernel (what serve hot-reload swaps in)."""
    return load_kernel(os.path.join(bundle, SNAPSHOT_KERNEL))
