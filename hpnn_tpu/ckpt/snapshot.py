"""On-disk snapshot format for the checkpoint subsystem.

A checkpoint directory holds a flat set of snapshot bundles plus one
manifest:

    <ckpt-dir>/
        manifest.json            latest tag, generation counter,
                                 fingerprints, error trajectory,
                                 retention policy, snapshot index
        ep00000003/              one bundle per checkpointed epoch
            kernel.opt           weights, reference text format
                                 (io.kernel_io -- loadable by run_nn,
                                 serve_nn and the compiled reference)
            state.npz            bit-exact training state: float64
                                 weights (w0..wN), BPM momentum buffers
                                 (m0..mN), the 33-word glibc shuffle-RNG
                                 state, epoch counter, effective seed
            snapshot.json        per-bundle manifest (tag, epoch, seed,
                                 fingerprint, mean error, topology)

Two weight encodings on purpose: the text format is the framework's
interop surface (``%17.15f`` quantizes -- fine for serving and for the
reference's own restart cycle), while ``state.npz`` carries the raw
float64 bits so ``train_nn --resume`` continues to a **byte-identical**
``kernel.opt`` versus the uninterrupted run (the repo's parity guarantee
extended across process death; pinned in tests/test_ckpt.py).

Crash safety: every bundle is staged under a dot-tmp directory, each
file fsync'd, then the DIRECTORY is renamed into place and the parent
fsync'd -- readers (the serve hot-reload watcher, a concurrent
``--resume``) see a complete bundle or none.  The manifest itself goes
through the shared ``io.atomic`` tmp+fsync+rename writer, and its
``generation`` counter increments on every publish, which is what the
serving registry's manifest watcher keys reloads on.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import io
import json
import os
import shutil
import time

import numpy as np

from ..io.atomic import atomic_write_text, fsync_dir
from ..io.kernel_io import dumps_kernel, encode_kernel_text, load_kernel
from ..models.kernel import Kernel

MANIFEST = "manifest.json"
SNAPSHOT_META = "snapshot.json"
SNAPSHOT_STATE = "state.npz"
SNAPSHOT_KERNEL = "kernel.opt"
MANIFEST_VERSION = 1


def snapshot_tag(epoch: int) -> str:
    return f"ep{int(epoch):08d}"


def fingerprint_bytes(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def fingerprint_file(path: str) -> str | None:
    try:
        with open(path, "rb") as fp:
            return fingerprint_bytes(fp.read())
    except OSError:
        return None


@dataclasses.dataclass
class SnapshotState:
    """Everything ``train_nn --resume`` restores."""

    weights: list[np.ndarray]          # float64, bit-exact
    momentum: list[np.ndarray] | None  # BPM dw buffers (None for BP)
    rng_state: list[int] | None        # glibc shuffle stream (33 words)
    epoch: int
    seed: int
    errors: list[float]                # per-epoch mean final error
    tag: str
    path: str                          # bundle directory
    fingerprint: str | None            # of kernel.opt in the bundle
    target_epochs: int = 0             # the run's --epochs goal (0: unknown)

    @property
    def topology(self) -> list[int]:
        return [int(self.weights[0].shape[1]),
                *[int(w.shape[0]) for w in self.weights]]


def _durable_write(path: str, data: bytes) -> None:
    """Plain write + fsync (used INSIDE a staged tmp bundle, where the
    directory rename provides the atomicity)."""
    with open(path, "wb") as fp:
        fp.write(data)
        fp.flush()
        os.fsync(fp.fileno())


def _state_npz_bytes(weights, momentum, rng_state, epoch: int,
                     seed: int) -> bytes:
    arrays = {f"w{i}": np.asarray(w, dtype=np.float64)
              for i, w in enumerate(weights)}
    if momentum is not None:
        arrays.update({f"m{i}": np.asarray(m, dtype=np.float64)
                       for i, m in enumerate(momentum)})
    if rng_state is not None:
        arrays["rng"] = np.asarray(rng_state, dtype=np.int64)
    arrays["meta"] = np.asarray([int(epoch), int(seed)], dtype=np.int64)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def write_snapshot(ckpt_dir: str, epoch: int, *, weights, momentum,
                   rng_state, seed: int, errors, name: str = "(null)",
                   train: str = "", dtype: str = "f64",
                   target_epochs: int = 0) -> dict:
    """Write one atomic bundle for ``epoch``; returns its index entry
    (tag/epoch/mean_err/fingerprint) for the manifest.

    Runs on the io_pool writer thread in production -- it must not
    print (the caller owns the console stream's byte parity).
    """
    os.makedirs(ckpt_dir, exist_ok=True)
    tag = snapshot_tag(epoch)
    final = os.path.join(ckpt_dir, tag)
    tmp = os.path.join(ckpt_dir, f".tmp.{tag}.{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        kernel_text = dumps_kernel(Kernel(name=name, weights=list(weights)))
        kernel_bytes = encode_kernel_text(kernel_text)
        fp_kernel = fingerprint_bytes(kernel_bytes)
        _durable_write(os.path.join(tmp, SNAPSHOT_KERNEL), kernel_bytes)
        _durable_write(os.path.join(tmp, SNAPSHOT_STATE),
                       _state_npz_bytes(weights, momentum, rng_state,
                                        epoch, seed))
        errors = [None if e is None else float(e) for e in errors]
        meta = {
            "tag": tag,
            "epoch": int(epoch),
            "seed": int(seed),
            "fingerprint": fp_kernel,
            "mean_err": errors[-1] if errors else None,
            "errors": errors,
            "topology": [int(weights[0].shape[1]),
                         *[int(w.shape[0]) for w in weights]],
            "train": train,
            "dtype": dtype,
            "momentum": momentum is not None,
            "target_epochs": int(target_epochs),
            "created": time.time(),
        }
        _durable_write(os.path.join(tmp, SNAPSHOT_META),
                       (json.dumps(meta, indent=1) + "\n").encode())
        fsync_dir(tmp)
        if os.path.isdir(final):  # re-snapshot of the same epoch
            shutil.rmtree(final)
        os.replace(tmp, final)
    except BaseException:
        with contextlib.suppress(OSError):
            shutil.rmtree(tmp)
        raise
    fsync_dir(ckpt_dir)
    return {"tag": tag, "epoch": int(epoch),
            "mean_err": meta["mean_err"], "fingerprint": fp_kernel}


# --- manifest ---------------------------------------------------------------

def manifest_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, MANIFEST)


def read_manifest(ckpt_dir: str) -> dict | None:
    """The checkpoint directory's manifest, or None when absent or
    unparseable (a half-created dir is not an error -- watchers poll)."""
    try:
        with open(manifest_path(ckpt_dir), "r") as fp:
            m = json.load(fp)
    except (OSError, json.JSONDecodeError):
        return None
    return m if isinstance(m, dict) else None


def write_manifest(ckpt_dir: str, manifest: dict) -> None:
    manifest = dict(manifest)
    manifest["version"] = MANIFEST_VERSION
    manifest["updated"] = time.time()
    atomic_write_text(manifest_path(ckpt_dir),
                      json.dumps(manifest, indent=1) + "\n")


def publish_snapshot(ckpt_dir: str, entry: dict, *, seed: int, errors,
                     keep_last: int = 0) -> dict:
    """Fold one bundle's index entry into the manifest (generation bump)
    and apply retention.  Returns the manifest written."""
    prev = read_manifest(ckpt_dir) or {}
    snaps = [s for s in prev.get("snapshots", [])
             if s.get("tag") != entry["tag"]]
    snaps.append(entry)
    snaps.sort(key=lambda s: s.get("epoch", 0))
    manifest = dict(prev)
    manifest.update({
        "generation": int(prev.get("generation", 0)) + 1,
        "latest": entry["tag"],
        "epoch": entry["epoch"],
        "seed": int(seed),
        "fingerprint": entry["fingerprint"],
        "kernel": os.path.join(entry["tag"], SNAPSHOT_KERNEL),
        "errors": [None if e is None else float(e) for e in errors],
        "retention": {"keep_last": int(keep_last), "keep_best": True},
        "snapshots": snaps,
    })
    manifest["snapshots"] = _apply_retention(ckpt_dir, snaps, keep_last)
    write_manifest(ckpt_dir, manifest)
    return manifest


def record_final_kernel(ckpt_dir: str, kernel_path: str) -> None:
    """Stamp the manifest with the path + fingerprint of the final
    ``kernel.opt`` train_nn wrote, so ``run_nn`` (and ops tooling) can
    detect a stale or hand-edited weights file (generation bump: a
    watching server hot-reloads the finished kernel)."""
    fp = fingerprint_file(kernel_path)
    if fp is None:
        return
    manifest = read_manifest(ckpt_dir) or {}
    manifest["generation"] = int(manifest.get("generation", 0)) + 1
    manifest["final_kernel"] = os.path.abspath(kernel_path)
    manifest["final_fingerprint"] = fp
    write_manifest(ckpt_dir, manifest)


def refresh_final_kernel(ckpt_dir: str, kernel_path: str) -> None:
    """Keep the manifest honest across PLAIN (non-checkpointed)
    retrains: when a manifest already tracks exactly this kernel file,
    re-record its fingerprint after a fresh dump -- otherwise every
    later ``run_nn`` would warn 'stale or modified weights' about a
    kernel that is actually NEWER than the manifest, training users to
    ignore the guard.  A no-op when no manifest tracks the file."""
    manifest = read_manifest(ckpt_dir)
    if not manifest:
        return
    if manifest.get("final_kernel") == os.path.abspath(kernel_path):
        record_final_kernel(ckpt_dir, kernel_path)


def _apply_retention(ckpt_dir: str, snaps: list[dict],
                     keep_last: int) -> list[dict]:
    """keep-last-N + best-by-error: the N most recent bundles always
    survive, and so does the lowest-mean-error one (keep_last <= 0 keeps
    everything).  Pruned bundles are deleted from disk."""
    if keep_last <= 0 or len(snaps) <= keep_last:
        return snaps
    by_epoch = sorted(snaps, key=lambda s: s.get("epoch", 0))
    keep = {s["tag"] for s in by_epoch[-keep_last:]}
    scored = [s for s in snaps if s.get("mean_err") is not None]
    if scored:
        keep.add(min(scored, key=lambda s: s["mean_err"])["tag"])
    kept = []
    for s in by_epoch:
        if s["tag"] in keep:
            kept.append(s)
            continue
        with contextlib.suppress(OSError):
            shutil.rmtree(os.path.join(ckpt_dir, s["tag"]))
    return kept


# --- resume ----------------------------------------------------------------

def _resolve_bundle(path: str) -> str | None:
    """Map a user-supplied ``--resume`` path to a bundle directory:
    accepts the checkpoint dir (-> manifest's latest), a bundle dir, or
    any file inside either."""
    path = os.path.abspath(path)
    if os.path.isfile(path):
        path = os.path.dirname(path)
    if not os.path.isdir(path):
        return None
    if os.path.isfile(os.path.join(path, SNAPSHOT_STATE)):
        return path
    manifest = read_manifest(path)
    if manifest and manifest.get("latest"):
        bundle = os.path.join(path, manifest["latest"])
        if os.path.isfile(os.path.join(bundle, SNAPSHOT_STATE)):
            return bundle
    # no manifest (crashed before first publish?): newest complete bundle
    tags = sorted(t for t in os.listdir(path)
                  if t.startswith("ep") and os.path.isfile(
                      os.path.join(path, t, SNAPSHOT_STATE)))
    return os.path.join(path, tags[-1]) if tags else None


def load_snapshot(path: str) -> SnapshotState | None:
    """Load a bundle (or a checkpoint dir's latest bundle) back into
    host state.  Weights come from ``state.npz`` -- bit-exact float64,
    NOT the quantized text -- which is what makes resume byte-identical.
    Returns None (with an NN(ERR) diagnostic) when nothing loadable is
    found."""
    from ..utils.nn_log import nn_error, nn_warn

    bundle = _resolve_bundle(path)
    if bundle is None:
        nn_error(f"CKPT: no resumable snapshot at {path}\n")
        return None
    try:
        with np.load(os.path.join(bundle, SNAPSHOT_STATE),
                     allow_pickle=False) as z:
            weights = [z[k] for k in sorted(
                (k for k in z.files if k.startswith("w")),
                key=lambda k: int(k[1:]))]
            momentum = [z[k] for k in sorted(
                (k for k in z.files if k.startswith("m") and k != "meta"),
                key=lambda k: int(k[1:]))] or None
            rng = [int(v) for v in z["rng"]] if "rng" in z.files else None
            epoch, seed = (int(v) for v in z["meta"])
    except (OSError, KeyError, ValueError) as exc:
        nn_error(f"CKPT: unreadable snapshot state in {bundle}: {exc}\n")
        return None
    meta = {}
    with contextlib.suppress(OSError, json.JSONDecodeError):
        with open(os.path.join(bundle, SNAPSHOT_META)) as fp:
            meta = json.load(fp)
    errors = [e for e in meta.get("errors", [])]
    fp_recorded = meta.get("fingerprint")
    fp_actual = fingerprint_file(os.path.join(bundle, SNAPSHOT_KERNEL))
    if fp_recorded and fp_actual and fp_recorded != fp_actual:
        nn_warn(f"CKPT: {os.path.join(bundle, SNAPSHOT_KERNEL)} does not "
                f"match its recorded fingerprint in "
                f"{os.path.join(bundle, SNAPSHOT_META)} -- resuming from "
                "state.npz anyway\n")
    return SnapshotState(weights=weights, momentum=momentum,
                         rng_state=rng, epoch=epoch, seed=seed,
                         errors=errors, tag=os.path.basename(bundle),
                         path=bundle, fingerprint=fp_actual,
                         target_epochs=int(meta.get("target_epochs", 0)))


def looks_like_checkpoint(path: str) -> bool:
    """Is ``path`` plausibly a checkpoint dir/bundle/file?  The CLI's
    ``--resume [PATH]`` grammar uses this to tell an optional resume
    path from the trailing conf filename."""
    if os.path.isdir(path):
        return (os.path.isfile(os.path.join(path, MANIFEST))
                or os.path.isfile(os.path.join(path, SNAPSHOT_STATE))
                or any(t.startswith("ep") for t in os.listdir(path)))
    return os.path.basename(path) in (MANIFEST, SNAPSHOT_META,
                                      SNAPSHOT_STATE)


def check_kernel_fingerprint(kernel_path: str | None,
                             ckpt_dir: str) -> bool:
    """``run_nn`` guard (satellite): when the checkpoint manifest has a
    recorded fingerprint for this exact kernel file and the bytes on
    disk no longer match, WARN with both paths instead of silently
    evaluating stale/modified weights.  Returns False on mismatch."""
    from ..utils.nn_log import nn_warn

    if not kernel_path:
        return True
    manifest = read_manifest(ckpt_dir)
    if not manifest:
        return True
    kp = os.path.abspath(kernel_path)
    recorded = None
    if manifest.get("final_kernel") == kp:
        recorded = manifest.get("final_fingerprint")
    elif manifest.get("kernel") and os.path.join(
            os.path.abspath(ckpt_dir), manifest["kernel"]) == kp:
        recorded = manifest.get("fingerprint")
    if not recorded:
        return True
    actual = fingerprint_file(kp)
    if actual is None or actual == recorded:
        return True
    nn_warn(f"kernel fingerprint mismatch: {kp} does not match the "
            f"manifest {manifest_path(os.path.abspath(ckpt_dir))} "
            "(stale or modified weights?)\n")
    return False


def load_bundle_kernel(bundle: str):
    """The bundle's text-format kernel (what serve hot-reload swaps in)."""
    return load_kernel(os.path.join(bundle, SNAPSHOT_KERNEL))
