"""CheckpointManager: epoch-boundary snapshots off the critical path.

The training loop hands the manager a *captured* copy of the mutable
state at each epoch boundary (weight list reference -- the epoch
replaces the list wholesale, never mutates it in place -- plus a copy
of the RNG words and the error trajectory) and keeps running; the
bundle is formatted and fsync'd on the shared ``io.corpus.io_pool``
executor, overlapping the next epoch's device work exactly the way the
corpus prefetcher does.  Writes are CHAINED through done-callbacks (a
queued snapshot is only submitted when its predecessor finishes) so
bundles and manifest generations land in epoch order while occupying
at most one pool thread -- a burst of snapshots can never starve the
corpus loader sharing the pool.

Console discipline: the manager prints its one ``CKPT: snapshot ...``
line synchronously on the training thread -- the async writer itself is
silenced (``nn_log.capture``) so background completion can never
interleave with the per-sample training stream, whose byte-for-byte
reproducibility is the repo's core guarantee (and the resume-parity
acceptance test compares whole console streams).

Failures are never dropped: the first writer exception is re-raised
from :meth:`flush` (the CLI flushes before declaring the run done).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..io.conf import NN_TRAIN_BPM
from ..obs import trace as obs_trace
from ..parallel import coord
from ..utils import nn_log
from ..utils.nn_log import nn_out
from . import snapshot as snap


class CheckpointManager:
    def __init__(self, ckpt_dir: str, every: int = 1, keep_last: int = 0,
                 use_pool: bool = True, target_epochs: int = 0,
                 replicate_to: str | None = None,
                 auth_token: str | None = None):
        self.ckpt_dir = ckpt_dir
        self.every = max(0, int(every))
        self.keep_last = max(0, int(keep_last))
        self.use_pool = use_pool
        # the run's --epochs goal, recorded in every bundle so a bare
        # --resume knows how far the interrupted run meant to go
        self.target_epochs = max(0, int(target_epochs))
        # off-host replication (ISSUE 14): each VERIFIED bundle is
        # shipped content-addressed to --replicate-to (a directory or a
        # mesh router) on its OWN io_pool future, deliberately outside
        # the snapshot chain flush() joins -- an unreachable
        # destination must never stall an epoch boundary (the jobs
        # scheduler flushes every due epoch).  Pending ships are
        # joined only at record_final (run end); failures warn +
        # count, never fail the run
        self.replicator = None
        self._rep_futures: list = []
        replicate_to = replicate_to \
            or os.environ.get("HPNN_REPLICATE_TO") or None
        if replicate_to:
            from .replicate import Replicator

            self.replicator = Replicator(replicate_to, ckpt_dir,
                                         auth_token=auth_token)
        self.errors: list[float | None] = []
        self.last_saved_epoch = 0
        self._future = None
        self._lock = threading.Lock()

    # --- trajectory -------------------------------------------------------
    def seed_errors(self, errors) -> None:
        """Carry the restored trajectory across a resume so the manifest
        keeps the WHOLE run's error curve."""
        self.errors = list(errors)

    # --- capture ----------------------------------------------------------
    def _capture(self, nn, epoch: int) -> dict:
        conf = nn.conf
        kernel = nn.kernel
        momentum = kernel.momentum
        if momentum is None and conf.train == NN_TRAIN_BPM:
            # the reference zeroes the dw buffers at every sample entry
            # (ann_raz_momentum, ann.c:2391) and frees them at epoch end,
            # so the canonical BPM momentum state AT an epoch boundary is
            # all-zeros -- that is what the bundle records
            momentum = [np.zeros_like(w) for w in kernel.weights]
        return {
            "weights": kernel.weights,  # replaced per epoch, safe to share
            "momentum": None if momentum is None
            else [np.array(m, dtype=np.float64) for m in momentum],
            "rng_state": (nn.shuffle_rng.get_state()
                          if nn.shuffle_rng is not None else None),
            "seed": int(conf.seed),
            "epoch": int(epoch),
            "errors": list(self.errors),
            "name": kernel.name,
            "train": conf.train,
            "dtype": conf.dtype,
            "target_epochs": self.target_epochs,
            # native-trainer carry (CG direction/grad/meta) -- copied so
            # the async writer sees the epoch-boundary state even if the
            # next epoch mutates it in place
            "trainer_state": ({k: np.array(v) for k, v in
                               nn.trainer_state.items()}
                              if getattr(nn, "trainer_state", None)
                              else None),
            # coherent-global-step stamp (ISSUE 18): bundles record the
            # world size that agreed on them behind the barrier
            "world_size": coord.world_size(),
        }

    # --- saving -----------------------------------------------------------
    def epoch_done(self, nn, epoch: int, mean_err: float | None) -> None:
        self.errors.append(None if mean_err is None else float(mean_err))
        if self.every and epoch % self.every == 0:
            self.save(nn, epoch)

    def save(self, nn, epoch: int, sync: bool = False) -> None:
        if coord.world_size() > 1:
            # the coherent global step (ISSUE 18): every rank reaches
            # this point at the same epoch (the training loop is
            # deterministic and stop flags are agreed at epoch
            # boundaries); the barrier proves it, then rank 0 alone
            # writes the bundle -- N ranks racing os.replace on one
            # shared checkpoint dir was the alternative.  The barrier
            # runs HERE, on the training thread, never on the async
            # writer (a pool-thread collective would race the next
            # epoch's device collectives).
            if not coord.snapshot_barrier(epoch):
                raise OSError(
                    f"snapshot barrier failed at epoch {epoch}: ranks "
                    "disagree on the bundle epoch (no bundle written)")
            if coord.process_index() != 0:
                self.last_saved_epoch = int(epoch)
                return
        job = self._capture(nn, epoch)
        self.last_saved_epoch = int(epoch)
        # the one console line, emitted HERE (deterministic position in
        # the training stream); the tag alone, so streams stay
        # comparable across different --ckpt-dir locations
        nn_out(f"CKPT: snapshot {snap.snapshot_tag(epoch)}\n")
        if sync or not self.use_pool:
            self.flush()
            with obs_trace.span("ckpt.snapshot_write", epoch=job["epoch"],
                                sync=True):
                self._write(job)
            return
        from concurrent.futures import Future

        from ..io.corpus import io_pool

        # snapshot-write spans parent under the CALLER's epoch span even
        # though the write runs on a pool thread: capture the context
        # here, record explicitly there (ISSUE 8)
        ctx = obs_trace.current_ctx()
        # bundles must land in epoch order, but the chain may never PARK
        # a pool worker waiting on its predecessor (queued snapshots
        # would otherwise occupy io_pool threads and starve the corpus
        # loader sharing the pool): each job is submitted from the
        # previous future's done-callback, so at most ONE pool thread
        # writes at any time
        fut = Future()
        with self._lock:
            prev = self._future
            self._future = fut
        if prev is None:
            io_pool().submit(self._run_job, job, fut, None, ctx)
        else:
            prev.add_done_callback(
                lambda p: io_pool().submit(self._run_job, job, fut, p,
                                           ctx))

    def _run_job(self, job: dict, fut, prev, ctx=None) -> None:
        if prev is not None and prev.exception() is not None:
            fut.set_exception(prev.exception())  # first failure wins
            return
        try:
            t0 = time.monotonic()
            with nn_log.capture():  # the writer never prints
                self._write(job)
            if obs_trace.enabled():
                obs_trace.record(
                    "ckpt.snapshot_write", t0, time.monotonic(),
                    trace_id=ctx[0] if ctx else None,
                    parent_id=ctx[1] if ctx else None,
                    epoch=job["epoch"], sync=False)
        except BaseException as exc:  # noqa: BLE001 -- surfaced at flush
            fut.set_exception(exc)
        else:
            fut.set_result(None)

    def _write(self, job: dict) -> None:
        entry = snap.write_snapshot(
            self.ckpt_dir, job["epoch"], weights=job["weights"],
            momentum=job["momentum"], rng_state=job["rng_state"],
            seed=job["seed"], errors=job["errors"], name=job["name"],
            train=job["train"], dtype=job["dtype"],
            target_epochs=job["target_epochs"],
            trainer_state=job.get("trainer_state"),
            world_size=job.get("world_size", 1))
        snap.publish_snapshot(self.ckpt_dir, entry, seed=job["seed"],
                              errors=job["errors"],
                              keep_last=self.keep_last)
        if self.replicator is not None:
            # only a bundle that passed its verified write ever ships;
            # replicate() swallows destination failures (warn + count).
            # A separate future, NOT this chain: flush() must never
            # wait on the network
            from ..io.corpus import io_pool

            with self._lock:
                self._rep_futures = [f for f in self._rep_futures
                                     if not f.done()]
                self._rep_futures.append(io_pool().submit(
                    self._replicate_silent,
                    os.path.join(self.ckpt_dir, entry["tag"])))

    def _replicate_silent(self, bundle_dir: str) -> None:
        with nn_log.capture():  # pool thread: never prints
            self.replicator.replicate(bundle_dir)

    def drain_replication(self) -> None:
        """Join every pending replica ship (each internally bounded by
        HPNN_REPLICATE_ATTEMPTS x HPNN_REPLICATE_TIMEOUT_S): called at
        run end so a finishing process does not cut its last bundles'
        replication short.  Failures were already warned + counted."""
        with self._lock:
            futures, self._rep_futures = self._rep_futures, []
        for fut in futures:
            with nn_log.capture():
                try:
                    fut.result()
                except Exception:  # noqa: BLE001 -- already surfaced
                    pass

    def flush(self) -> None:
        """Block until every queued bundle is durably published;
        re-raises the first writer failure."""
        with self._lock:
            fut = self._future
            self._future = None
        if fut is not None:
            fut.result()

    def record_final(self, kernel_path: str) -> None:
        """After train_nn's final ``kernel.opt`` dump: flush pending
        bundles, then stamp the manifest with the final kernel's path +
        fingerprint (run_nn's staleness guard; watchers see the bump).
        Pending replica ships are joined here too -- the run's end is
        the one place waiting on the destination is correct."""
        self.flush()
        if coord.process_index() == 0:
            # rank 0 owns the shared manifest, same as the bundles
            snap.record_final_kernel(self.ckpt_dir, kernel_path)
        self.drain_replication()
