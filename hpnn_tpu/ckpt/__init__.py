"""Checkpoint & model-lifecycle subsystem.

Crash-safe epoch-boundary snapshots, bit-exact ``train_nn --resume``,
and the manifest contract the serving registry's hot-reload watcher
consumes.  See ``snapshot.py`` for the on-disk format, ``manager.py``
for the async (io_pool) writer, ``trainer.py`` for the multi-epoch
driver with SIGTERM/SIGINT final-snapshot handling.
"""

from .manager import CheckpointManager
from .replicate import Replicator, pack_bundle, restore_bundle, unpack_bundle
from .snapshot import (
    MANIFEST,
    SNAPSHOT_KERNEL,
    SNAPSHOT_META,
    SNAPSHOT_STATE,
    SnapshotState,
    candidate_bundles,
    check_kernel_fingerprint,
    fingerprint_bytes,
    fingerprint_file,
    load_bundle_kernel,
    load_snapshot,
    looks_like_checkpoint,
    manifest_path,
    publish_snapshot,
    read_manifest,
    record_final_kernel,
    refresh_final_kernel,
    snapshot_tag,
    verify_bundle,
    write_manifest,
    write_snapshot,
)
from .trainer import train_loop

__all__ = [
    "CheckpointManager", "MANIFEST", "SNAPSHOT_KERNEL", "SNAPSHOT_META",
    "SNAPSHOT_STATE", "SnapshotState", "candidate_bundles",
    "check_kernel_fingerprint",
    "fingerprint_bytes", "fingerprint_file", "load_bundle_kernel",
    "load_snapshot", "looks_like_checkpoint", "manifest_path", "publish_snapshot",
    "read_manifest", "record_final_kernel", "refresh_final_kernel", "snapshot_tag", "train_loop",
    "verify_bundle", "write_manifest", "write_snapshot",
    "Replicator", "pack_bundle", "unpack_bundle", "restore_bundle",
]
