"""Multi-epoch training driver with resumable, crash-safe state.

The reference trains in 1+N *rounds*: each round is a fresh process
that reloads ``kernel.opt`` and re-seeds the shuffle
(``tutorials/mnist/tutorial.bash:125-197``).  ``train_nn --epochs K``
runs the same per-sample convergence epochs **in one process**: the
kernel stays host-resident between epochs and the seeded glibc shuffle
stream CONTINUES across them (one ``srandom`` at the start, each
epoch's shuffle consuming the next draws) -- deterministic, so the
whole K-epoch trajectory is a pure function of (conf, corpus, seed).

That determinism is what makes checkpoint/resume *bit-exact*: a bundle
written at the epoch-k boundary (weights + BPM momentum + RNG words +
epoch counter) fully determines epochs k+1..K, so an interrupted run
resumed with ``--resume`` replays the identical console stream and
lands on a byte-identical ``kernel.opt`` (tests/test_ckpt.py pins
both, for BP and BPM).

SIGTERM/SIGINT do not kill the run mid-epoch: the handler latches a
stop flag, the loop finishes the in-flight device epoch, writes a
final synchronous snapshot, and exits cleanly.  ``HPNN_CKPT_KILL_AT_EPOCH=k``
drives that same handler path deterministically (the resume-parity
tests send the signal from inside, at an exact epoch boundary).
"""

from __future__ import annotations

import os
import signal
import threading
import time

from ..parallel import coord
from ..utils.glibc_random import GlibcRandom
from ..utils.nn_log import nn_out
from .manager import CheckpointManager


def _install_handlers(stop: threading.Event):
    """Latch ``stop`` on SIGTERM/SIGINT; returns the previous handlers
    (restored on exit).  Only the main thread may install -- elsewhere
    (tests driving the loop from a worker) signals keep their default
    behavior."""
    if threading.current_thread() is not threading.main_thread():
        return None

    def handler(signum, frame):
        stop.set()

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, handler)
        except (ValueError, OSError):  # pragma: no cover
            pass
    return prev


def _restore_handlers(prev) -> None:
    if not prev:
        return
    for sig, old in prev.items():
        try:
            signal.signal(sig, old)
        except (ValueError, OSError):  # pragma: no cover
            pass


def train_loop(nn, epochs: int, manager: CheckpointManager | None = None,
               start_epoch: int = 0,
               rng_state: list[int] | None = None,
               stop: threading.Event | None = None,
               on_epoch=None) -> tuple[bool, bool]:
    """Run epochs ``start_epoch+1 .. epochs``; returns
    ``(trained_ok, interrupted)``.

    ``stop`` (jobs subsystem): an EXTERNAL stop event shared with the
    caller -- a job cancel or a server drain latches it exactly like a
    SIGTERM would, the in-flight epoch finishes and the final snapshot
    is written; without one the loop owns a private event wired to the
    signal handlers (the train_nn behavior, unchanged).

    ``on_epoch(epoch, manager)`` is called at every epoch boundary
    (after the epoch's checkpoint bookkeeping, before the interruption
    checks): the jobs scheduler uses it to flush due snapshots into the
    serving registry and to YIELD to queued eval traffic -- the
    epoch-granularity time-slice of the shared device.  The callback
    may block; it runs on the training thread.

    ``rng_state`` (from a snapshot) restores the shuffle stream;
    otherwise the stream starts fresh from ``conf.seed`` (seed 0 ->
    time(), written back -- the reference's ``srandom`` semantics,
    libhpnn.c:1218).  The per-epoch banner prints only on multi-epoch
    or resumed runs, so a plain single-epoch ``train_nn`` stays
    byte-identical to the reference stream.

    Epoch pipeline (ISSUE 5): when ``train_kernel`` activates the
    device-resident pipeline, this loop becomes its join-point driver --
    per-sample console lines and the stats readback for epoch k are
    rendered on the io_pool while epoch k+1 runs on device, and the
    queue drains (in byte order: lines, banners, CKPT messages) only at
    snapshot boundaries, interruption, or run end -- exactly where the
    float64 host weights are needed anyway.  The drained epoch
    summaries feed the manager's error trajectory in epoch order, so
    the manifest is indistinguishable from the unpipelined run.
    """
    from ..api import (pipeline_active, pipeline_defer_out, pipeline_join,
                       train_kernel)
    from ..obs import trace as obs_trace

    conf = nn.conf
    if rng_state is not None:
        nn.shuffle_rng = GlibcRandom.from_state(rng_state)
    elif nn.shuffle_rng is None:
        if conf.seed == 0:
            conf.seed = int(time.time())
        nn.shuffle_rng = GlibcRandom(conf.seed)

    from ..utils.env import env_int

    kill_at = env_int("HPNN_CKPT_KILL_AT_EPOCH", 0)
    world = coord.world_size()
    banner = epochs > 1 or start_epoch > 0
    if stop is None:
        stop = threading.Event()
    prev_handlers = _install_handlers(stop)
    interrupted = False
    last_epoch = start_epoch
    # epochs whose (deferred) summaries have not reached the manager yet
    pending: list[int] = []

    def drain() -> None:
        """Join the pipeline's deferred epochs in order: console bytes
        replay, host weights sync, manager trajectory/saves catch up."""
        sums = pipeline_join(nn)
        for ep, summary in zip(pending, sums):
            if manager is not None:
                manager.epoch_done(nn, ep,
                                   summary.get("mean_final")
                                   if summary else None)
        del pending[:]

    nn._pipeline_defer = True  # train_kernel leaves joins to this loop
    try:
        for epoch in range(start_epoch + 1, epochs + 1):
            last_epoch = epoch
            # the per-epoch span root (ISSUE 8): train_kernel's phases
            # (load/gather/device launch), the deferred-stats drain, the
            # snapshot write and the jobs scheduler's epoch callback
            # (hot swap + eval yield) all nest under it via the
            # thread-local span stack -- a no-op when tracing is off
            epoch_span = obs_trace.span("train.epoch", epoch=epoch,
                                        epochs=epochs)
            with epoch_span:
                if banner:
                    text = f"EPOCH {epoch:8d}/{epochs:8d}\n"
                    if not pipeline_defer_out(nn, text):
                        nn_out(text)
                if not train_kernel(nn):
                    drain()
                    return False, False
                # coordinated stop (ISSUE 18): a SIGTERM/cancel caught
                # by ONE rank latches the stop on EVERY rank at this
                # epoch boundary, so nobody runs ahead into the next
                # epoch's collectives alone and the final snapshot's
                # barrier sees all ranks.  Single-process: a plain read.
                stopping = stop.is_set()
                if world > 1:
                    stopping = coord.any_flag(stopping)
                    if stopping:
                        stop.set()
                if pipeline_active(nn):
                    pending.append(epoch)
                    # join only where the unpipelined loop would need
                    # the host state: a due snapshot, the final epoch, a
                    # latched signal, or the deterministic kill hook
                    # about to fire
                    due = (manager is not None and manager.every
                           and epoch % manager.every == 0)
                    if (due or epoch == epochs or stop.is_set()
                            or (kill_at and epoch == kill_at)):
                        drain()
                else:
                    stats = getattr(nn, "last_epoch_stats", None)
                    mean_err = stats.get("mean_final") if stats else None
                    if manager is not None:
                        manager.epoch_done(nn, epoch, mean_err)
                if on_epoch is not None:
                    on_epoch(epoch, manager)
            if kill_at and epoch == kill_at and epoch < epochs:
                # exercise the REAL signal path at a deterministic
                # boundary (test hook; see module docstring)
                os.kill(os.getpid(), signal.SIGTERM)
            # multi-process: only the AGREED stop may enter the
            # interrupt path (save's barrier needs every rank); a
            # late local signal waits one epoch for agreement
            if (stopping if world > 1 else stop.is_set()) \
                    and epoch < epochs:
                interrupted = True
                drain()  # a signal may land between the join check and
                # here: the final snapshot below must see synced weights
                if manager is not None:
                    # final snapshot, synchronous: the process is about
                    # to exit, nothing may stay queued
                    if manager.last_saved_epoch != epoch:
                        manager.save(nn, epoch, sync=True)
                    manager.flush()
                    nn_out(f"CKPT: interrupted at epoch {epoch}/{epochs};"
                           " state saved -- continue with train_nn "
                           "--resume\n")
                else:
                    nn_out(f"CKPT: interrupted at epoch {epoch}/{epochs} "
                           "(checkpointing off; partial state only in "
                           "kernel.opt)\n")
                break
        if (not interrupted and manager is not None
                and last_epoch > start_epoch
                and manager.last_saved_epoch != last_epoch):
            # clean completion off the --ckpt-every grid (incl. every=0):
            # the FINAL epoch always gets a bundle, so the manifest's
            # latest kernel is the finished model (what --watch-ckpt
            # servers swap in) and a later --resume sees the true end
            # state
            manager.save(nn, last_epoch)
    finally:
        drain()  # safety net: no deferred bytes/weights may outlive the run
        nn._pipeline_defer = False
        _restore_handlers(prev_handlers)
        if manager is not None:
            manager.flush()
    return True, interrupted
