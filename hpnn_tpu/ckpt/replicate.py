"""Off-host checkpoint bundle replication (ISSUE 14, tentpole c).

Snapshots used to exist only on the training host: a dead disk (or a
dead host) lost every bundle at once, which is exactly the failure
mode checkpoint-restart is supposed to survive (Awan et al.,
arXiv:1810.11112; Ericson & Mbuvha, arXiv:1701.05130 both assume the
checkpoint outlives the worker).  This module ships every VERIFIED
bundle somewhere else, content-addressed, and can restore the newest
intact one on any host:

* **container** -- :func:`pack_bundle` serializes one bundle
  (``kernel.opt`` + ``state.npz`` + ``snapshot.json``) into a single
  deterministic blob: magic, JSON header with per-file sizes and
  sha256s plus the bundle's manifest kernel fingerprint, then the raw
  file bytes.  The blob's own sha256 is its address;
  :func:`unpack_bundle` re-verifies every file hash before a byte
  lands on disk.
* **destinations** -- ``--replicate-to DIR`` writes
  ``<DIR>/<scope>/<sha256>.bundle`` (atomic, via ``io.atomic``) plus an
  ``index.json``; ``--replicate-to http://HOST:PORT`` POSTs the blob to
  a mesh router's ``/v1/mesh/bundle`` endpoint, which stores it in the
  PR-11 content-addressed :class:`~..serve.mesh.router.BlobStore` and
  indexes it per scope -- any surviving host can then pull it back
  through the ordinary ``GET /v1/mesh/blob/<sha>`` path.
* **scope** -- one checkpoint stream's identity
  (:func:`scope_for`: the ckpt dir's basename + a stable hash of its
  absolute path), so one destination serves many jobs without
  collisions and a restarted host finds ITS stream again.
* **transport discipline** -- router-mode ships ride
  ``mesh.transport`` (keep-alive pool + jittered
  :class:`~..serve.mesh.transport.Backoff`, bounded attempts); the
  caller (``CheckpointManager``) runs :meth:`Replicator.replicate`
  async on the shared ``io_pool``, so replication overlaps the next
  epoch exactly like the bundle write itself.
* **restore** -- :func:`restore_bundle` walks the destination's index
  newest-first, verifies each blob's sha256 AND the unpacked bundle's
  recorded fingerprints (``snapshot.verify_bundle``), and materializes
  the newest intact bundle into a local checkpoint dir -- the
  last-good-fallback walk, extended off-host.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time

from ..utils.nn_log import nn_dbg, nn_warn
from . import snapshot as snap

_MAGIC = b"HPNNBNDL"
_VERSION = 1
# the bundle files a replica carries, in container order
_FILES = (snap.SNAPSHOT_KERNEL, snap.SNAPSHOT_STATE, snap.SNAPSHOT_META)
_INDEX = "index.json"


class ReplicateError(Exception):
    """A bundle could not be shipped to, or restored from, a replica
    destination."""


def scope_for(ckpt_dir: str) -> str:
    """A checkpoint stream's default replica identity: readable
    basename + a hash of the absolute path (two jobs named ``ckpt`` on
    one host must not collide at the destination).  Path-derived, so
    recovery from a DIFFERENT host needs the checkpoint dir to resolve
    to the same absolute path -- cross-path recovery sets an explicit
    ``HPNN_REPLICATE_SCOPE`` on both ends (:func:`resolve_scope`)."""
    path = os.path.abspath(ckpt_dir)
    digest = hashlib.sha256(path.encode("utf-8")).hexdigest()[:12]
    base = os.path.basename(path.rstrip(os.sep)) or "ckpt"
    safe = "".join(c if c.isalnum() or c in "._-" else "_"
                   for c in base)[:40]
    return f"{safe}-{digest}"


def resolve_scope(ckpt_dir: str, scope: str | None = None) -> str:
    """The replica scope every ship AND restore site uses: an explicit
    argument, else ``HPNN_REPLICATE_SCOPE`` (the cross-host recovery
    knob -- set it identically on the shipping and recovering side),
    else the path-derived default."""
    return scope or os.environ.get("HPNN_REPLICATE_SCOPE") \
        or scope_for(ckpt_dir)


# --- container --------------------------------------------------------------

def pack_bundle(bundle_dir: str) -> tuple[bytes, dict]:
    """Serialize one on-disk bundle into a single content-addressed
    blob; returns ``(blob, meta)`` where meta carries the blob sha256,
    tag/epoch and the kernel fingerprint cross-checkable against the
    checkpoint manifest.  Raises :class:`ReplicateError` on an
    incomplete bundle."""
    files = []
    payloads = []
    for name in _FILES:
        try:
            with open(os.path.join(bundle_dir, name), "rb") as fp:
                data = fp.read()
        except OSError as exc:
            raise ReplicateError(
                f"bundle {bundle_dir} incomplete: {name}: {exc}")
        files.append({"name": name, "size": len(data),
                      "sha256": hashlib.sha256(data).hexdigest()})
        payloads.append(data)
    meta = {}
    try:
        meta = json.loads(payloads[_FILES.index(snap.SNAPSHOT_META)]
                          .decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        pass
    header = {"version": _VERSION, "tag": os.path.basename(bundle_dir),
              "epoch": int(meta.get("epoch", 0) or 0),
              "kernel_fingerprint": meta.get("fingerprint"),
              "files": files}
    blob = json.dumps(header, separators=(",", ":")).encode("utf-8")
    out = _MAGIC + struct.pack("<Q", len(blob)) + blob + b"".join(payloads)
    return out, {"sha256": hashlib.sha256(out).hexdigest(),
                 "size": len(out), "tag": header["tag"],
                 "epoch": header["epoch"],
                 "kernel_fingerprint": header["kernel_fingerprint"]}


def read_bundle_header(data: bytes) -> tuple[dict, int]:
    """(header, payload offset) of a packed bundle blob; raises
    :class:`ReplicateError` on any structural problem."""
    if len(data) < 16 or data[:8] != _MAGIC:
        raise ReplicateError("not a packed bundle (bad magic)")
    (hlen,) = struct.unpack("<Q", data[8:16])
    if hlen > 1 << 30 or len(data) < 16 + hlen:
        raise ReplicateError("truncated bundle header")
    try:
        header = json.loads(data[16:16 + hlen].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise ReplicateError(f"bad bundle header: {exc}")
    if not isinstance(header, dict) or header.get("version") != _VERSION:
        raise ReplicateError("unsupported bundle version")
    return header, 16 + hlen


def unpack_bundle(data: bytes, dest_dir: str) -> str:
    """Materialize a packed bundle under ``dest_dir/<tag>``, verifying
    every file's recorded sha256 BEFORE anything is renamed into place
    (staged-dir + rename, same crash discipline as the snapshot
    writer).  Returns the bundle path."""
    import shutil

    header, off = read_bundle_header(data)
    tag = str(header.get("tag") or "")
    if not tag.startswith("ep"):
        raise ReplicateError(f"bad bundle tag {tag!r}")
    final = os.path.join(dest_dir, tag)
    tmp = os.path.join(dest_dir, f".tmp.restore.{tag}.{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        for entry in header.get("files", []):
            name = os.path.basename(str(entry.get("name", "")))
            size = int(entry.get("size", -1))
            if name not in _FILES or size < 0 \
                    or off + size > len(data):
                raise ReplicateError(f"bad file entry {entry!r}")
            chunk = data[off:off + size]
            off += size
            if hashlib.sha256(chunk).hexdigest() != entry.get("sha256"):
                raise ReplicateError(f"{name}: sha256 mismatch in "
                                     "packed bundle")
            with open(os.path.join(tmp, name), "wb") as fp:
                fp.write(chunk)
                fp.flush()
                os.fsync(fp.fileno())
        snap.fsync_dir(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    snap.fsync_dir(dest_dir)
    return final


# --- destinations -----------------------------------------------------------

def _is_http(dest: str) -> bool:
    return dest.startswith(("http://", "https://"))


def _router_addr(dest: str) -> str:
    addr = dest.split("://", 1)[1].rstrip("/")
    return addr


class Replicator:
    """Ships verified bundles to one destination (see module doc).

    ``replicate`` is synchronous and bounded -- the CheckpointManager
    submits it to the io_pool so the training loop never blocks on the
    network; a permanently failing destination costs a warning per
    bundle, never the run."""

    def __init__(self, dest: str, ckpt_dir: str,
                 scope: str | None = None,
                 auth_token: str | None = None,
                 attempts: int | None = None,
                 timeout_s: float | None = None):
        from ..utils.env import env_float, env_int

        self.dest = dest
        self.scope = resolve_scope(ckpt_dir, scope)
        self.auth_token = auth_token \
            or os.environ.get("HPNN_SERVE_TOKEN") or None
        self.attempts = (attempts if attempts is not None
                         else env_int("HPNN_REPLICATE_ATTEMPTS", 3,
                                      lo=1))
        self.timeout_s = (timeout_s if timeout_s is not None
                          else env_float("HPNN_REPLICATE_TIMEOUT_S",
                                         20.0, lo=0.1))
        self.shipped_total = 0
        self.failed_total = 0
        self.last_error: str | None = None
        self.last_lag_s: float | None = None

    def _headers(self) -> dict:
        if self.auth_token:
            return {"Authorization": f"Bearer {self.auth_token}"}
        return {}

    # --- ship ------------------------------------------------------------
    def replicate(self, bundle_dir: str) -> dict | None:
        """Pack + ship one bundle; returns its replica meta (sha256,
        size, tag, lag_s) or None on permanent failure (warned, counted
        -- replication is belt-and-braces, the local bundle already
        verified)."""
        t0 = time.monotonic()
        try:
            blob, meta = pack_bundle(bundle_dir)
            if _is_http(self.dest):
                self._ship_http(blob, meta)
            else:
                self._ship_dir(blob, meta)
        except (ReplicateError, OSError) as exc:
            self.failed_total += 1
            self.last_error = f"{type(exc).__name__}: {exc}"
            nn_warn(f"CKPT: replication of {bundle_dir} to {self.dest} "
                    f"failed: {self.last_error}\n")
            return None
        self.shipped_total += 1
        self.last_error = None
        self.last_lag_s = round(time.monotonic() - t0, 4)
        meta["lag_s"] = self.last_lag_s
        nn_dbg(f"CKPT: replicated {meta['tag']} "
               f"({meta['size']} B, sha {meta['sha256'][:12]}...) to "
               f"{self.dest} in {meta['lag_s']}s\n")
        return meta

    def _ship_dir(self, blob: bytes, meta: dict) -> None:
        from ..utils.env import env_int

        root = os.path.join(os.path.abspath(self.dest), self.scope)
        write_scope_blob(root, blob, meta["sha256"])
        update_scope_index(
            root,
            {k: meta[k] for k in ("sha256", "size", "tag", "epoch",
                                  "kernel_fingerprint")},
            # retention: a multi-hundred-epoch run must not grow the
            # replica without bound (the local dir's keep-last already
            # bounds what resume can want)
            keep=env_int("HPNN_REPLICATE_KEEP", 64, lo=1))

    def _ship_http(self, blob: bytes, meta: dict) -> None:
        from ..serve.mesh import transport

        addr = _router_addr(self.dest)
        backoff = transport.Backoff(base_s=0.2, cap_s=5.0)
        headers = dict(self._headers())
        headers["Content-Type"] = "application/octet-stream"
        path = (f"/v1/mesh/bundle?scope={self.scope}"
                f"&tag={meta['tag']}&epoch={meta['epoch']}")
        last = "no attempt"
        for i in range(self.attempts):
            if i:
                time.sleep(backoff.next_delay())
            try:
                status, raw, _ = transport.request(
                    addr, "POST", path, body=blob, headers=headers,
                    timeout_s=self.timeout_s)
            except transport.TRANSPORT_ERRORS as exc:
                last = f"{type(exc).__name__}: {exc}"
                continue
            if status != 200:
                last = f"HTTP {status}: {raw[:120]!r}"
                continue
            try:
                ack = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                ack = {}
            if ack.get("sha256") != meta["sha256"]:
                # the router stored SOMETHING, but not our bytes
                last = f"router sha mismatch ({ack.get('sha256')})"
                continue
            return
        raise ReplicateError(
            f"router {addr} refused bundle after {self.attempts} "
            f"attempt(s): {last}")

    def stats(self) -> dict:
        return {"dest": self.dest, "scope": self.scope,
                "shipped_total": self.shipped_total,
                "failed_total": self.failed_total,
                "last_lag_s": self.last_lag_s,
                "last_error": self.last_error}


# --- the shared directory-spool protocol ------------------------------------
# One on-disk format for BOTH sides of replication: the Replicator's
# DIR destination and the router's durable bundle spool
# (serve/mesh/router.py) write sha-addressed ``<sha>.bundle`` files
# plus one ``index.json`` per scope through these helpers, so the
# format lives in exactly one place and each side can read the
# other's spool.

def read_scope_index(root: str) -> list[dict]:
    """The scope dir's index entries (empty on absent/corrupt)."""
    try:
        with open(os.path.join(root, _INDEX)) as fp:
            doc = json.load(fp)
    except (OSError, ValueError, UnicodeDecodeError):
        return []
    bundles = doc.get("bundles") if isinstance(doc, dict) else None
    return [b for b in bundles or []
            if isinstance(b, dict) and b.get("sha256")]


def write_scope_blob(root: str, blob: bytes, sha256: str) -> str:
    """Land one content-addressed blob in the scope dir (atomic,
    idempotent).  Returns the path."""
    from ..io.atomic import atomic_write_bytes

    os.makedirs(root, exist_ok=True)
    dest = os.path.join(root, f"{sha256}.bundle")
    if not os.path.isfile(dest):
        atomic_write_bytes(dest, blob)
    return dest


def update_scope_index(root: str, entry: dict, keep: int) -> list[dict]:
    """Fold one entry into the scope index: dedup by sha256, sort by
    (epoch, tag) -- tolerating entries missing either field -- trim to
    the newest ``keep``, atomically rewrite ``index.json``, unlink
    pruned blobs.  Returns the kept entries (newest last)."""
    from ..io.atomic import atomic_write_text

    index = read_scope_index(root)
    index = [e for e in index if e.get("sha256") != entry["sha256"]]
    index.append(entry)
    index.sort(key=lambda e: (e.get("epoch", 0), e.get("tag", "")))
    pruned, index = index[:-keep], index[-keep:]
    atomic_write_text(os.path.join(root, _INDEX),
                      json.dumps({"version": 1, "bundles": index},
                                 indent=1) + "\n")
    for old in pruned:
        try:
            os.unlink(os.path.join(root, f"{old.get('sha256')}.bundle"))
        except OSError:
            pass
    return index


# --- restore ----------------------------------------------------------------


def list_replicated(dest: str, scope: str,
                    auth_token: str | None = None) -> list[dict]:
    """The destination's replica index for one scope, oldest-first
    (same order both destination kinds)."""
    if _is_http(dest):
        from ..serve.mesh import transport

        headers = ({"Authorization": f"Bearer {auth_token}"}
                   if auth_token else {})
        try:
            status, raw, _ = transport.request(
                _router_addr(dest), "GET",
                f"/v1/mesh/bundles?scope={scope}", headers=headers,
                timeout_s=10.0)
        except transport.TRANSPORT_ERRORS as exc:
            raise ReplicateError(f"cannot list replicas on {dest}: "
                                 f"{type(exc).__name__}: {exc}")
        if status != 200:
            raise ReplicateError(f"cannot list replicas on {dest}: "
                                 f"HTTP {status}")
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            raise ReplicateError(f"bad replica index from {dest}: {exc}")
        bundles = doc.get("bundles") if isinstance(doc, dict) else None
        return [b for b in bundles or [] if isinstance(b, dict)]
    return read_scope_index(os.path.join(os.path.abspath(dest), scope))


def _fetch_blob(dest: str, scope: str, entry: dict,
                auth_token: str | None) -> bytes | None:
    sha = str(entry.get("sha256") or "")
    if _is_http(dest):
        from ..serve.mesh import transport

        headers = ({"Authorization": f"Bearer {auth_token}"}
                   if auth_token else {})
        try:
            status, raw, _ = transport.request(
                _router_addr(dest), "GET", f"/v1/mesh/blob/{sha}",
                headers=headers, timeout_s=20.0)
        except transport.TRANSPORT_ERRORS:
            return None
        if status != 200:
            return None
    else:
        try:
            with open(os.path.join(os.path.abspath(dest), scope,
                                   f"{sha}.bundle"), "rb") as fp:
                raw = fp.read()
        except OSError:
            return None
    if hashlib.sha256(raw).hexdigest() != sha:
        return None
    return raw


def restore_bundle(dest: str, scope: str, into_dir: str,
                   auth_token: str | None = None) -> str | None:
    """Materialize the NEWEST intact replicated bundle of ``scope``
    into ``into_dir`` (a checkpoint dir): blob sha256 verified, files
    verified on unpack, and the landed bundle verified once more
    against its own recorded fingerprints.  Walks older replicas on
    any failure; returns the restored bundle path or None."""
    try:
        index = list_replicated(dest, scope, auth_token=auth_token)
    except ReplicateError as exc:
        nn_warn(f"CKPT: cannot restore from {dest}: {exc}\n")
        return None
    for entry in sorted(index, key=lambda e: (e.get("epoch", 0),
                                              e.get("tag", "")),
                        reverse=True):
        raw = _fetch_blob(dest, scope, entry, auth_token)
        if raw is None:
            nn_warn(f"CKPT: replica {entry.get('sha256', '?')[:12]}... "
                    f"of {scope} unreadable/corrupt on {dest}; trying "
                    "older\n")
            continue
        try:
            os.makedirs(into_dir, exist_ok=True)
            bundle = unpack_bundle(raw, into_dir)
        except (ReplicateError, OSError) as exc:
            nn_warn(f"CKPT: replica {entry.get('tag')} failed to "
                    f"unpack: {exc}; trying older\n")
            continue
        ok, reason = snap.verify_bundle(bundle)
        if not ok:
            nn_warn(f"CKPT: restored replica {bundle} failed "
                    f"verification ({reason}); trying older\n")
            continue
        return bundle
    return None
