"""Corpus ingestion pipeline: parallel loader + packed sample cache.

SCALE_MNIST60K showed the host-side corpus load (60k tiny text files
opened and parsed serially) burning ~6.3 s of every ~25 s warm round
while the device epoch is ~8 s -- the classic "input pipeline starves
the accelerator" wall.  This module kills that tax in three layers while
preserving the driver's bit-parity and log-byte-parity guarantees:

1. **Parallel loader** -- per-file reads fan across a shared thread pool
   driving the GIL-releasing native reader (``samples.read_sample_fast``
   -> ``native/libhpnn_io.so`` via ctypes; declines fall back to the
   Python parser inside the worker).  Rows are assembled in the exact
   seeded-shuffle order, and each worker CAPTURES its would-be console
   output (``nn_log.capture``) so the assembly loop can REPLAY skip
   diagnostics at exactly the position the serial loop emitted them --
   the stderr stream is byte-identical to the serial path.

2. **Packed corpus cache** -- the first load of a sample/test dir writes
   one binary pack (header JSON with a fingerprint of the dir listing,
   sizes, mtimes and per-file status codes + contiguous x/t float64
   arrays) as a dotfile SIBLING of the dir (never inside it -- the
   listing the seeded shuffle runs over must not change), or under
   ``--corpus-cache DIR`` / ``HPNN_CORPUS_CACHE``.  Warm loads mmap the
   pack and skip the per-file walk entirely; any listing/size/mtime/dims
   change invalidates the pack and falls back to per-file reads (which
   rebuild it).  ``HPNN_NO_CORPUS_CACHE=1`` bypasses packing entirely.

3. **Overlap** -- ``load_ordered_async`` runs the whole load on a
   background thread (console output deferred to ``result()`` so the
   stream stays byte-stable) while the caller warms the device path;
   ``prefetch_pack_async`` builds another dir's pack silently in the
   background (``api.train_kernel`` prefetches the test dir during the
   training epoch so the following ``run_nn`` warm-loads).

Replayed console output makes the three paths indistinguishable at the
byte level: pack-cache replay reconstructs the exact diagnostic strings
(read failures keyed by path, dimension mismatches by name) from status
codes, and a file whose diagnostics do not match a replayable pattern
simply makes the dir unpackable (correctness first, cache second).

Built on top of the pack, the **device-resident epoch pipeline** (ISSUE
5): :func:`load_resident` hands the multi-epoch training driver ONE
listing-order copy of the corpus (rows + per-file status codes), so
every later epoch is a host-computed permutation + an on-device gather
instead of a re-walk/re-stage -- see ``api._EpochPipeline``.  The
per-epoch console bytes (headers in shuffle order, skip diagnostics)
are reconstructed from the status codes by :meth:`ResidentCorpus.
epoch_events`, the same replay rule the warm pack path uses.

Env knobs: ``HPNN_IO_THREADS`` (pool width; default min(32, cpus)),
``HPNN_NO_PARALLEL_IO=1`` (serial reads), ``HPNN_NO_CORPUS_CACHE=1``
(no pack read/write/prefetch), ``HPNN_CORPUS_CACHE=DIR`` (pack
location), ``HPNN_CORPUS_CACHE_MAX_MB`` (LRU cap on the shared cache
dir), plus samples.py's ``HPNN_NO_NATIVE_IO``/``HPNN_IO_LIB``.
"""

from __future__ import annotations

import contextlib
import glob
import hashlib
import json
import os
import struct
import threading
import time

import numpy as np

from ..utils import nn_log
from ..utils.env import env_int
from ..utils.nn_log import nn_dbg, nn_error, nn_warn
from . import samples
from .samples import read_sample_fast

_PACK_MAGIC = b"HPNNPK01"
_PACK_VERSION = 1
_ALIGN = 64
# content-integrity trailer (ISSUE 14): sha256 over header blob + data
# region, appended AFTER the data so pre-trailer readers (which check
# size with >=) keep working.  Warm mmap loads verify it once per
# process; a corrupt pack rebuilds with a warning instead of serving
# garbage rows.
_PACK_TRAILER_MAGIC = b"HPNNSH01"
# packs this process already content-verified, keyed by (path, size,
# mtime_ns) so an invalidated/rebuilt pack re-verifies
_verified_packs: dict[tuple, None] = {}
_VERIFIED_PACKS_MAX = 64

# per-file status codes stored in the pack (listing order); >= 0 is the
# row index into the packed x/t arrays
_ST_SILENT = -1    # unopenable/empty file: (None, None), no diagnostic
_ST_IN_FAIL = -2   # "sample <path> input read failed!" on stderr
_ST_OUT_FAIL = -3  # "sample <path> output read failed!" on stderr
_ST_DIM = -4       # driver-level "dimension mismatch, skipped!"
_LOADED = "loaded"

_cache_dir_override: str | None = None
_cache_max_mb_override: int | None = None
# packs in active use by THIS process (loaded or built for a live run):
# the corpus-cache GC never evicts them, whatever their LRU age.  The
# registry is insertion-ordered and BOUNDED: a long-lived process (a
# server warm-loading many corpora over months) must not accumulate an
# exemption for every pack it ever touched, or the LRU cap silently
# stops evicting -- protection is per-run best-effort, and losing it for
# an ancient pack merely costs that pack a rebuild on its next load.
_ACTIVE_PACKS_MAX = 16
_active_packs: dict[str, None] = {}


def _note_active(path: str) -> None:
    ap = os.path.abspath(path)
    _active_packs.pop(ap, None)          # re-insertion refreshes the age
    _active_packs[ap] = None
    while len(_active_packs) > _ACTIVE_PACKS_MAX:
        _active_packs.pop(next(iter(_active_packs)))


_pool = None
_pool_lock = threading.Lock()


# --- knobs ------------------------------------------------------------------

def cache_enabled() -> bool:
    return not os.environ.get("HPNN_NO_CORPUS_CACHE")


def set_cache_dir(path: str | None) -> None:
    """Explicit pack location (the CLI's ``--corpus-cache DIR``); wins
    over the HPNN_CORPUS_CACHE env var."""
    global _cache_dir_override
    _cache_dir_override = path


def _cache_dir() -> str | None:
    return _cache_dir_override or os.environ.get("HPNN_CORPUS_CACHE") or None


def set_cache_max_mb(mb: int | None) -> None:
    """LRU size cap for the shared corpus-cache dir (the CLI's
    ``--corpus-cache-max-mb``); wins over HPNN_CORPUS_CACHE_MAX_MB.
    0/None disables the cap."""
    global _cache_max_mb_override
    _cache_max_mb_override = None if mb is None else int(mb)


def _cache_max_bytes() -> int:
    if _cache_max_mb_override is not None:
        return _cache_max_mb_override << 20
    return env_int("HPNN_CORPUS_CACHE_MAX_MB", 0, lo=0) << 20


def gc_cache(protect: tuple[str, ...] = ()) -> list[str]:
    """Evict least-recently-used packs from the shared cache dir until it
    fits under the configured cap (0 = no cap = no-op).  LRU age is the
    pack mtime -- warm loads bump it (:func:`_try_load_pack`), so a pack
    in steady use never goes stale.  Packs named in ``protect`` or
    registered by this process's live runs (``_active_packs``) are never
    evicted; sibling dotfile packs (no shared cache dir) are out of
    scope, there is no one place to enumerate them.  Returns the evicted
    paths (for the dbg line and the tests)."""
    cap = _cache_max_bytes()
    cdir = _cache_dir()
    if not cap or not cdir or not os.path.isdir(cdir):
        return []
    entries = []
    for p in glob.glob(os.path.join(cdir, "corpus-*.pack")):
        try:
            st = os.stat(p)
        except OSError:
            continue
        entries.append((st.st_mtime_ns, st.st_size, os.path.abspath(p)))
    total = sum(e[1] for e in entries)
    keep = set(os.path.abspath(p) for p in protect) | set(_active_packs)
    evicted = []
    for mtime, size, path in sorted(entries):
        if total <= cap:
            break
        if path in keep:
            continue
        try:
            os.unlink(path)
        except OSError:
            continue
        # the pack's flock sibling goes with it (benign if another
        # process holds it right now: the worst case is one duplicate
        # build, and a leaked lock would otherwise outlive its pack
        # forever in a capped cache dir)
        try:
            os.unlink(path + ".lock")
        except OSError:
            pass
        total -= size
        evicted.append(path)
    if evicted:
        nn_dbg(f"corpus cache: evicted {len(evicted)} LRU pack(s) "
               f"over the {cap >> 20} MB cap\n")
    return evicted


@contextlib.contextmanager
def _pack_build_lock(dirpath: str):
    """flock-guarded critical section for building ``dirpath``'s pack:
    two processes cold-loading the same corpus dir serialize here, and
    the waiter re-probes the winner's pack (fingerprint-checked) instead
    of re-reading every file.  Yields True when the lock is held; any
    OS-level failure degrades to the old unlocked behavior (a duplicate
    build is wasteful, never wrong -- pack writes are atomic replaces).
    The lock file rides next to the pack; a crashed holder's lock is
    released by the kernel with its fd."""
    path = pack_path(dirpath) + ".lock"
    fd = None
    try:
        import fcntl

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        fcntl.flock(fd, fcntl.LOCK_EX)
    except Exception:
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        yield False
        return
    try:
        yield True
    finally:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        except OSError:
            pass
        try:
            os.close(fd)
        except OSError:
            pass


def io_threads() -> int:
    # a SET knob always wins, clamped to >= 1 (HPNN_IO_THREADS=0 means
    # serial, exactly like the pre-consolidation max(1, int(env)));
    # malformed degrades to 1 -- the safe width -- not to auto sizing
    if os.environ.get("HPNN_IO_THREADS"):
        return env_int("HPNN_IO_THREADS", 1, lo=1)
    if os.environ.get("HPNN_NO_PARALLEL_IO"):
        return 1
    return max(1, min(32, os.cpu_count() or 1))


def io_pool():
    """Shared background executor for corpus reads, prefetch packing and
    serve warmup compiles -- one bounded pool per process instead of
    ad-hoc per-call thread spawns.  Created lazily; width fixed at first
    use (HPNN_IO_THREADS)."""
    global _pool
    with _pool_lock:
        if _pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _pool = ThreadPoolExecutor(max_workers=io_threads(),
                                       thread_name_prefix="hpnn-io")
        return _pool


def pack_path(dirpath: str) -> str:
    """Pack location for a sample dir: a dotfile SIBLING (never inside --
    the in-dir listing feeds the seeded shuffle and scripts count it), or
    a hash-keyed file under the corpus-cache dir when configured."""
    ap = os.path.abspath(dirpath)
    cdir = _cache_dir()
    if cdir:
        key = hashlib.sha1(ap.encode()).hexdigest()[:20]
        return os.path.join(cdir, f"corpus-{key}.pack")
    return os.path.join(os.path.dirname(ap),
                        f".{os.path.basename(ap)}.hpnn.pack")


# --- fingerprint ------------------------------------------------------------

def _stat_listing(dirpath: str, names: list[str]):
    """(sizes, mtimes_ns) for the listing, or None if any entry fails to
    stat (the dir is then unpackable/unverifiable).

    This pass IS the warm-load cost (the whole point of the pack is
    that nothing else touches the 60k files), so big listings fan the
    stat syscalls across the shared pool -- os.stat releases the GIL.
    Contiguous chunks keep the result aligned with the listing order.
    """

    def stat_chunk(chunk):
        out = []
        for n in chunk:
            st = os.stat(os.path.join(dirpath, n))
            out.append((st.st_size, st.st_mtime_ns))
        return out

    try:
        k = min(io_threads(), 16)
        if k > 1 and len(names) > 512:
            step = -(-len(names) // k)
            futs = [io_pool().submit(stat_chunk,
                                     names[i * step:(i + 1) * step])
                    for i in range(k)]
            pairs = [p for f in futs for p in f.result()]
        else:
            pairs = stat_chunk(names)
    except OSError:
        return None
    return [p[0] for p in pairs], [p[1] for p in pairs]


# --- pack read --------------------------------------------------------------

def _read_pack_header(path: str):
    """(header dict, data offset) or None on any structural problem."""
    try:
        with open(path, "rb") as fp:
            if fp.read(8) != _PACK_MAGIC:
                return None
            raw = fp.read(8)
            if len(raw) != 8:
                return None
            (hlen,) = struct.unpack("<Q", raw)
            if hlen > 1 << 30:
                return None
            blob = fp.read(hlen)
            if len(blob) != hlen:
                return None
            hdr = json.loads(blob.decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    if not isinstance(hdr, dict) or hdr.get("version") != _PACK_VERSION:
        return None
    return hdr, _aligned(16 + hlen)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _pack_content_ok(path: str, data_end: int) -> bool:
    """Content-integrity check for a warm pack load: hash the header +
    data region and compare against the trailer sha256, ONCE per
    process per (path, trailer) -- the trailer digest itself keys the
    memo, so the LRU mtime bumps never force a re-hash but a rebuilt
    pack always gets one.  Packs without a trailer (pre-ISSUE-14)
    pass: their stat fingerprint is the only guard they ever had."""
    try:
        with open(path, "rb") as fp:
            fp.seek(data_end)
            trailer = fp.read(8 + 32)
            if trailer[:8] != _PACK_TRAILER_MAGIC or len(trailer) != 40:
                return True  # legacy pack: no trailer to enforce
            key = (os.path.abspath(path), trailer)
            if key in _verified_packs:
                return True
            fp.seek(0)
            h = hashlib.sha256()
            remaining = data_end
            while remaining > 0:
                chunk = fp.read(min(1 << 20, remaining))
                if not chunk:
                    return False  # shrank under us
                h.update(chunk)
                remaining -= len(chunk)
            if h.digest() != trailer[8:]:
                return False
    except OSError:
        return False
    _verified_packs[key] = None
    while len(_verified_packs) > _VERIFIED_PACKS_MAX:
        _verified_packs.pop(next(iter(_verified_packs)))
    return True


def _try_load_pack(dirpath: str, names: list[str], n_in: int, n_out: int,
                   probe_only: bool = False):
    """Validate the pack against the CURRENT dir state; returns
    (status, X, T) memmap-backed on a hit, True on a probe-only hit,
    None on any miss (missing/stale/corrupt -> caller re-reads)."""
    path = pack_path(dirpath)
    got = _read_pack_header(path)
    if got is None:
        return None
    hdr, data_off = got
    if hdr.get("n_in") != n_in or hdr.get("n_out") != n_out:
        return None
    if hdr.get("names") != names:
        return None  # added/removed/reordered files
    stats = _stat_listing(dirpath, names)
    if stats is None:
        return None
    sizes, mtimes = stats
    if hdr.get("sizes") != sizes or hdr.get("mtimes") != mtimes:
        return None  # touched/resized files
    status = hdr.get("status")
    n_rows = hdr.get("n_rows")
    if (not isinstance(status, list) or len(status) != len(names)
            or not isinstance(n_rows, int)):
        return None
    need = data_off + n_rows * (n_in + n_out) * 8
    try:
        if os.path.getsize(path) < need:
            return None  # truncated write
    except OSError:
        return None
    if probe_only:
        return True
    if not _pack_content_ok(path, need):
        # bit-rot/torn bytes under a valid header: rebuild from the
        # source files instead of serving garbage rows (ISSUE 14)
        nn_warn(f"corpus cache: {path} failed its content sha256; "
                "rebuilding the pack from source files\n")
        with contextlib.suppress(OSError):
            os.unlink(path)
        return None
    # LRU bookkeeping for the cache GC: a served pack is a recently-used
    # pack (content is fingerprinted by the header, not the mtime, so
    # the bump cannot stale-serve anything); registration protects the
    # in-flight run's pack from eviction
    try:
        os.utime(path)
    except OSError:
        pass
    _note_active(path)
    if n_rows == 0:
        return status, None, None
    X = np.memmap(path, dtype=np.float64, mode="r", offset=data_off,
                  shape=(n_rows, n_in))
    T = np.memmap(path, dtype=np.float64, mode="r",
                  offset=data_off + n_rows * n_in * 8,
                  shape=(n_rows, n_out))
    return status, X, T


def _order_events(dirpath, names, order, header, status,
                  lines: list[str] | None = None):
    """Shuffle-order replay of per-file status codes: the header events
    and skip diagnostics, byte-identical to what the per-file read path
    emits.  Returns (events, sel) where sel holds the PACKED row index
    of each loaded file in shuffle order.  ``lines`` optionally supplies
    pre-formatted header lines (listing order) -- the resident pipeline
    caches them across epochs."""
    rows, events = [], []
    for idx in order:
        name = names[idx]
        line = (lines[idx] if lines is not None
                else f"{header} FILE: {name[:16]:>16}\t")
        st = status[idx]
        if st >= 0:
            events.append((line, len(rows)))
            rows.append(st)
            continue
        if st == _ST_IN_FAIL:
            nn_error(f"sample {os.path.join(dirpath, name)} "
                     "input read failed!\n")
        elif st == _ST_OUT_FAIL:
            nn_error(f"sample {os.path.join(dirpath, name)} "
                     "output read failed!\n")
        elif st == _ST_DIM:
            nn_error(f"sample {name} dimension mismatch, skipped!\n")
        events.append((line, None))
    return events, np.asarray(rows, dtype=np.int32)


def _assemble_pack(dirpath, names, order, header, status, X, T):
    """Replay a pack in shuffle order: identical events, rows and
    diagnostic bytes to what the per-file path produces."""
    events, sel = _order_events(dirpath, names, order, header, status)
    if sel.size == 0:
        return events, None, None
    # fancy indexing a memmap copies just the selected pages into fresh
    # host arrays -- the "stream pack slices" handoff point
    return events, np.asarray(X[sel]), np.asarray(T[sel])


# --- per-file reads ---------------------------------------------------------

def _quiet_read(path: str, n_in: int, n_out: int):
    """One file read with its console output captured for ordered
    replay; runs on pool workers and inline alike."""
    with nn_log.capture() as diags:
        vec_in, vec_out = read_sample_fast(path, n_in, n_out)
    return vec_in, vec_out, diags


def _read_results(dirpath: str, names: list[str], n_in: int, n_out: int):
    """All files read (listing order submission, per-file capture);
    returns (results list indexed like names, mode string)."""
    # probe the native lib ONCE on this thread so its one-time warning
    # (if any) lands in this thread's stream, not inside a worker capture
    samples._native()
    paths = [os.path.join(dirpath, n) for n in names]
    if io_threads() <= 1 or len(paths) <= 2:
        return [_quiet_read(p, n_in, n_out) for p in paths], "serial"
    pool = io_pool()
    futs = [pool.submit(_quiet_read, p, n_in, n_out) for p in paths]
    return [f.result() for f in futs], "parallel"


def _assemble_results(dirpath, names, order, header, n_in, n_out, results):
    """The driver's skip/diagnostic semantics (``libhpnn.c:1230-1242``),
    identical to the old serial ``api._load_ordered`` loop -- captured
    diagnostics replay at the exact position the serial read emitted
    them."""
    xs, ts, events = [], [], []
    for idx in order:
        name = names[idx]
        # NN_OUT(stdout,"%s FILE: %16.16s\t") -- printed before the read
        line = f"{header} FILE: {name[:16]:>16}\t"
        vec_in, vec_out, diags = results[idx]
        nn_log.replay(diags)
        if vec_in is None or vec_out is None:
            events.append((line, None))
            continue
        if vec_in.shape[0] < n_in or vec_out.shape[0] < n_out:
            # a section count SMALLER than the kernel dimension makes the
            # reference copy past its allocation (libhpnn.c:1243, undefined
            # behavior); we skip with a diagnostic -- documented deviation
            nn_error(f"sample {name} dimension mismatch, skipped!\n")
            events.append((line, None))
            continue
        # a LARGER count is deterministic in the reference: it copies the
        # first kernel-dimension values and ignores the rest -- truncate
        events.append((line, len(xs)))
        xs.append(vec_in[:n_in])
        ts.append(vec_out[:n_out])
    if not xs:
        return events, None, None
    return events, np.stack(xs), np.stack(ts)


# --- pack write -------------------------------------------------------------

def _classify(dirpath, name, vec_in, vec_out, diags, n_in, n_out):
    """Status code for one read result, or None when its diagnostics do
    not match a replayable pattern (the dir is then not packed)."""
    if vec_in is None or vec_out is None:
        if not diags:
            return _ST_SILENT
        if len(diags) == 1 and diags[0][0] == "error":
            path = os.path.join(dirpath, name)
            if diags[0][1] == f"sample {path} input read failed!\n":
                return _ST_IN_FAIL
            if diags[0][1] == f"sample {path} output read failed!\n":
                return _ST_OUT_FAIL
        return None
    if diags:
        return None
    if vec_in.shape[0] < n_in or vec_out.shape[0] < n_out:
        return _ST_DIM
    return _LOADED


def _save_pack(dirpath, names, n_in, n_out, results, stats) -> bool:
    """Best-effort pack write from fresh read results (atomic replace;
    rows stored in LISTING order so the pack is shuffle-seed
    independent).  Any anomaly -> no pack, never an error.

    ``stats`` is the fingerprint captured BEFORE the reads: a file
    modified mid-load then carries a pre-modification stat, so the next
    load sees the mismatch and rebuilds -- stat-after-read would bake
    the stale rows in with a fresh fingerprint and serve them forever.
    """
    if stats is None:
        return False
    status, rows_x, rows_t = [], [], []
    for idx, name in enumerate(names):
        vec_in, vec_out, diags = results[idx]
        st = _classify(dirpath, name, vec_in, vec_out, diags, n_in, n_out)
        if st is None:
            nn_dbg(f"corpus cache: {name} has non-replayable "
                   "diagnostics; dir not packed\n")
            return False
        if st is _LOADED:
            status.append(len(rows_x))
            rows_x.append(np.ascontiguousarray(vec_in[:n_in], np.float64))
            rows_t.append(np.ascontiguousarray(vec_out[:n_out], np.float64))
        else:
            status.append(st)
    sizes, mtimes = stats
    hdr = {"version": _PACK_VERSION, "n_in": n_in, "n_out": n_out,
           "n_rows": len(rows_x), "names": names,
           "sizes": sizes, "mtimes": mtimes, "status": status}
    blob = json.dumps(hdr, separators=(",", ":")).encode("utf-8")
    path = pack_path(dirpath)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # sweep tmp litter from prefetch daemons killed mid-write by a
        # past interpreter exit (atomic replace means none was ever
        # served); ours is re-created just below
        for stale in glob.glob(f"{path}.tmp.*"):
            try:
                os.unlink(stale)
            except OSError:
                pass
        with open(tmp, "wb") as fp:
            fp.write(_PACK_MAGIC)
            fp.write(struct.pack("<Q", len(blob)))
            fp.write(blob)
            fp.write(b"\0" * (_aligned(16 + len(blob)) - 16 - len(blob)))
            if rows_x:
                np.stack(rows_x).tofile(fp)
                np.stack(rows_t).tofile(fp)
        # content trailer (ISSUE 14): sha256 over the whole header +
        # data region, appended AFTER the data so older readers are
        # unaffected (streamed re-read -- never a second in-memory copy
        # of a multi-hundred-MB corpus)
        digest = hashlib.sha256()
        with open(tmp, "rb") as fp:
            for chunk in iter(lambda: fp.read(1 << 20), b""):
                digest.update(chunk)
        with open(tmp, "ab") as fp:
            fp.write(_PACK_TRAILER_MAGIC)
            fp.write(digest.digest())
        os.replace(tmp, path)
    except OSError as exc:
        nn_dbg(f"corpus cache: pack write failed ({exc})\n")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    _note_active(path)
    gc_cache(protect=(path,))
    return True


# --- chunked streaming ingest (ISSUE 18 rung 2) -----------------------------

_CHUNK_MAGIC = b"HPNNCK01"


def _read_chunk(path: str):
    """(header dict, data offset) of one chunk file, verified against
    its own sha256 trailer; None on any structural or integrity
    problem.  Chunks are small (one upload body), so the verify pass
    streams the file once."""
    try:
        with open(path, "rb") as fp:
            if fp.read(8) != _CHUNK_MAGIC:
                return None
            raw = fp.read(8)
            if len(raw) != 8:
                return None
            (hlen,) = struct.unpack("<Q", raw)
            if hlen > 1 << 30:
                return None
            blob = fp.read(hlen)
            if len(blob) != hlen:
                return None
            hdr = json.loads(blob.decode("utf-8"))
            if not isinstance(hdr, dict) \
                    or hdr.get("version") != _PACK_VERSION:
                return None
            data_off = _aligned(16 + hlen)
            n_rows = hdr.get("n_rows")
            n_in, n_out = hdr.get("n_in"), hdr.get("n_out")
            if not all(isinstance(v, int)
                       for v in (n_rows, n_in, n_out)):
                return None
            data_end = data_off + n_rows * (n_in + n_out) * 8
            fp.seek(data_end)
            trailer = fp.read(8 + 32)
            if trailer[:8] != _PACK_TRAILER_MAGIC or len(trailer) != 40:
                return None
            fp.seek(0)
            h = hashlib.sha256()
            remaining = data_end
            while remaining > 0:
                piece = fp.read(min(1 << 20, remaining))
                if not piece:
                    return None
                h.update(piece)
                remaining -= len(piece)
            if h.digest() != trailer[8:]:
                return None
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    return hdr, data_off


class ChunkedPackWriter:
    """Incremental pack build: a corpus enters the packed-cache format
    one chunk at a time (ISSUE 18 rung 2 -- the jobs chunked upload
    appends each body's rows while later chunks are still in flight).

    Each :meth:`add_chunk` writes a self-contained chunk file next to
    the final pack path, carrying its own header and sha256 trailer, so
    a torn or bit-rotted chunk is detected at :meth:`finalize` before a
    single row reaches the assembled pack.  ``finalize`` streams the
    verified chunks into the standard ``HPNNPK01`` layout (all X rows,
    then all T rows, content trailer, atomic replace) -- the result is
    indistinguishable from a :func:`_save_pack` of the whole dir, so
    the warm-load path needs no changes.
    """

    def __init__(self, dirpath: str, n_in: int, n_out: int):
        self.dirpath = dirpath
        self.n_in = int(n_in)
        self.n_out = int(n_out)
        self._pack = pack_path(dirpath)
        self._chunks: list[str] = []
        self._names: list[str] = []
        self._n_rows = 0
        self._broken = False

    @property
    def n_rows(self) -> int:
        return self._n_rows

    @property
    def n_chunks(self) -> int:
        return len(self._chunks)

    def add_chunk(self, names, status, X, T) -> bool:
        """Append one chunk: ``status`` maps each of ``names`` to a row
        index LOCAL to this chunk (>= 0) or a skip class (< 0); ``X``/
        ``T`` hold the chunk's loaded rows.  Returns False (and poisons
        the writer) on any write failure -- the corpus still trains
        from its source files, it just doesn't get the warm pack."""
        if self._broken:
            return False
        n_rows = 0 if X is None else int(X.shape[0])
        hdr = {"version": _PACK_VERSION, "seq": len(self._chunks),
               "n_in": self.n_in, "n_out": self.n_out,
               "n_rows": n_rows, "names": list(names),
               "status": [int(s) for s in status]}
        blob = json.dumps(hdr, separators=(",", ":")).encode("utf-8")
        path = f"{self._pack}.chunk{len(self._chunks):05d}"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            digest = hashlib.sha256()
            with open(path, "wb") as fp:
                head = (_CHUNK_MAGIC + struct.pack("<Q", len(blob))
                        + blob
                        + b"\0" * (_aligned(16 + len(blob))
                                   - 16 - len(blob)))
                fp.write(head)
                digest.update(head)
                if n_rows:
                    xb = np.ascontiguousarray(
                        X[:, :self.n_in], np.float64).tobytes()
                    tb = np.ascontiguousarray(
                        T[:, :self.n_out], np.float64).tobytes()
                    fp.write(xb)
                    fp.write(tb)
                    digest.update(xb)
                    digest.update(tb)
                fp.write(_PACK_TRAILER_MAGIC)
                fp.write(digest.digest())
        except OSError as exc:
            nn_dbg(f"corpus cache: chunk write failed ({exc})\n")
            self._broken = True
            return False
        self._chunks.append(path)
        self._names.extend(names)
        self._n_rows += n_rows
        return True

    def add_sample_files(self, names) -> bool:
        """Read ``names`` (relative to the writer's dir) with the normal
        corpus readers, classify their diagnostics, and append them as
        one chunk.  False when any file's diagnostics are
        non-replayable (dir can't be packed) or the chunk write fails."""
        if self._broken:
            return False
        results, _mode = _read_results(self.dirpath, list(names),
                                       self.n_in, self.n_out)
        classified = _classify_results(self.dirpath, list(names),
                                       self.n_in, self.n_out, results)
        if classified is None:
            self._broken = True
            return False
        status, X, T = classified
        return self.add_chunk(names, status, X, T)

    def finalize(self) -> bool:
        """Verify every chunk's sha256 trailer and assemble the standard
        pack (atomic replace; chunk files removed on success).

        The pack format stores rows in the dir's READDIR listing order
        (the reference's shuffle substrate), which is unknowable while
        chunks are still arriving -- so assembly reorders: the dir is
        listed NOW, every listed name is located in its chunk, and rows
        are streamed out in listing order (per-row reads from the chunk
        files, never a full in-memory corpus).  A listing that does not
        match the uploaded set -- a file added or removed behind the
        writer's back -- refuses the pack instead of baking a stale
        one.  The fingerprint (sizes/mtimes) is stat'd now too:
        uploaded files are immutable once written, and any later touch
        invalidates the pack exactly like _save_pack."""
        if self._broken or not self._chunks:
            self.abort()
            return False
        listing = samples.list_sample_dir(self.dirpath)
        if listing is None or sorted(listing) != sorted(self._names):
            nn_dbg("corpus cache: dir listing does not match the "
                   "uploaded chunks; chunked pack skipped\n")
            self.abort()
            return False
        stats = _stat_listing(self.dirpath, listing)
        if stats is None:
            self.abort()
            return False
        heads = []
        for path in self._chunks:
            got = _read_chunk(path)
            if got is None:
                nn_warn(f"corpus cache: chunk {os.path.basename(path)} "
                        "failed its sha256; chunked pack abandoned\n")
                self.abort()
                return False
            heads.append(got)
        # name -> (skip class | local row, chunk index, data offset)
        where: dict = {}
        for ci, (chdr, data_off) in enumerate(heads):
            for name, st in zip(chdr["names"], chdr["status"]):
                where[name] = (int(st), ci, data_off)
        status, plan = [], []
        for name in listing:
            st, ci, data_off = where[name]
            if st >= 0:
                status.append(len(plan))
                plan.append((ci, data_off, st))
            else:
                status.append(st)
        sizes, mtimes = stats
        hdr = {"version": _PACK_VERSION, "n_in": self.n_in,
               "n_out": self.n_out, "n_rows": len(plan),
               "names": listing, "sizes": sizes, "mtimes": mtimes,
               "status": status}
        blob = json.dumps(hdr, separators=(",", ":")).encode("utf-8")
        tmp = f"{self._pack}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as out:
                out.write(_PACK_MAGIC)
                out.write(struct.pack("<Q", len(blob)))
                out.write(blob)
                out.write(b"\0" * (_aligned(16 + len(blob))
                                   - 16 - len(blob)))
                # pack layout is all-X-then-all-T in listing order: two
                # row-granular passes over the chunk files
                for region in ("x", "t"):
                    row_b = 8 * (self.n_in if region == "x"
                                 else self.n_out)
                    fps = {}
                    try:
                        for ci, data_off, local_row in plan:
                            fp = fps.get(ci)
                            if fp is None:
                                fp = fps[ci] = open(self._chunks[ci],
                                                    "rb")
                            skip = (heads[ci][0]["n_rows"] * self.n_in
                                    * 8 if region == "t" else 0)
                            fp.seek(data_off + skip + local_row * row_b)
                            piece = fp.read(row_b)
                            if len(piece) != row_b:
                                raise OSError(
                                    f"chunk {self._chunks[ci]} "
                                    "truncated")
                            out.write(piece)
                    finally:
                        for fp in fps.values():
                            with contextlib.suppress(OSError):
                                fp.close()
            digest = hashlib.sha256()
            with open(tmp, "rb") as fp:
                for piece in iter(lambda: fp.read(1 << 20), b""):
                    digest.update(piece)
            with open(tmp, "ab") as fp:
                fp.write(_PACK_TRAILER_MAGIC)
                fp.write(digest.digest())
            os.replace(tmp, self._pack)
        except OSError as exc:
            nn_dbg(f"corpus cache: chunked pack assembly failed "
                   f"({exc})\n")
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            self.abort()
            return False
        self.abort()  # chunk files are spent either way
        _note_active(self._pack)
        gc_cache(protect=(self._pack,))
        return True

    def abort(self) -> None:
        """Remove the chunk files (idempotent)."""
        for path in self._chunks:
            with contextlib.suppress(OSError):
                os.unlink(path)
        self._chunks = []


# --- the loader entry points ------------------------------------------------

def load_ordered(dirpath: str, names: list[str], order: list[int],
                 header: str, n_in: int, n_out: int):
    """Read samples in shuffled order -- pack-cache fast path, then
    parallel per-file reads (building the pack), byte-identical console
    output either way.

    Returns (events, X, T): events is a list of (header_line, row) pairs
    in shuffle order; row is None for skipped files (their header is
    still printed, unterminated, exactly like the reference which emits
    the "FILE: name\\t" header before attempting the read).
    """
    t0 = time.perf_counter()
    mode, out = None, (None, None, None)
    if cache_enabled() and n_in > 0 and n_out > 0:
        got = _try_load_pack(dirpath, names, n_in, n_out)
        if got is not None:
            status, X, T = got
            out = _assemble_pack(dirpath, names, order, header, status, X, T)
            mode = "pack"
    if mode is None:
        packing = cache_enabled() and n_in > 0 and n_out > 0
        with _pack_build_lock(dirpath) if packing \
                else contextlib.nullcontext(False) as locked:
            if locked:
                # a concurrent builder may have won the lock first:
                # re-probe, and mmap ITS pack instead of re-reading the
                # whole dir (fingerprint still checked against the
                # current dir state)
                got = _try_load_pack(dirpath, names, n_in, n_out)
                if got is not None:
                    status, X, T = got
                    out = _assemble_pack(dirpath, names, order, header,
                                         status, X, T)
                    mode = "pack"
            if mode is None:
                # fingerprint BEFORE the reads (see _save_pack's
                # stale-write note)
                stats = _stat_listing(dirpath, names) if packing else None
                results, mode = _read_results(dirpath, names, n_in, n_out)
                out = _assemble_results(dirpath, names, order, header,
                                        n_in, n_out, results)
                if packing:
                    _save_pack(dirpath, names, n_in, n_out, results, stats)
    events, X, T = out
    # load-stats line (dbg level: the -vv console stream is a byte-parity
    # surface across ingestion modes, so the mode name cannot print there)
    nn_dbg(f"load: {len(names)} file(s), "
           f"{0 if X is None else X.shape[0]} row(s) in "
           f"{time.perf_counter() - t0:.3f}s ({mode}; "
           f"native_io: {samples.native_io_status()})\n")
    return events, X, T


class ResidentCorpus:
    """One listing-order copy of a corpus, loaded ONCE per run for the
    device-resident epoch pipeline (``api._EpochPipeline``).

    ``X``/``T`` hold the loaded rows in PACKED (listing) order -- the
    pack's own layout, shuffle-seed independent -- and ``status`` maps
    each listing index to its packed row (>= 0) or skip class (< 0).
    Every epoch's console bytes and device gather indices derive from
    these via :meth:`epoch_events`, so after the first load no epoch
    touches the corpus files again."""

    def __init__(self, dirpath: str, names: list[str], status: list[int],
                 X, T, header: str = "TRAINING"):
        self.dirpath = dirpath
        self.names = names
        self.status = status
        self.X = X            # (n_rows, n_in) f64, listing order (or None)
        self.T = T
        self.header = header
        self._n_rows = 0 if X is None else int(X.shape[0])
        # header lines are epoch-invariant: format the 60k strings once
        self._lines = [f"{header} FILE: {n[:16]:>16}\t" for n in names]

    @property
    def n_rows(self) -> int:
        return self._n_rows

    def release_rows(self) -> None:
        """Drop the host row arrays once a device-resident copy exists
        (epoch replay needs only names/status/headers); sharded mode
        keeps them -- it gathers every epoch's shards from here."""
        self.X = None
        self.T = None

    def epoch_events(self, order: list[int]):
        """(events, sel) for one epoch's shuffle order; emits the skip
        diagnostics (stderr) exactly like the per-file load would."""
        return _order_events(self.dirpath, self.names, order, self.header,
                             self.status, lines=self._lines)

    def padded_row_block(self, which: str, lo: int, hi: int,
                         total_rows: int):
        """Rows ``[lo, hi)`` of X (``which='x'``) or T as a contiguous
        f64 block, with zero rows standing in past ``n_rows`` (the DP
        pad up to ``total_rows``).  This is the per-host shard feed for
        the cross-process resident upload (ISSUE 18): when the rows are
        pack-backed memmaps, only the requested row-range's pages are
        ever touched -- no host materializes the full corpus."""
        src = self.X if which == "x" else self.T
        if not 0 <= lo <= hi <= total_rows:
            raise ValueError(f"row block [{lo}, {hi}) outside "
                             f"[0, {total_rows})")
        width = int(src.shape[1]) if src is not None else 0
        real_hi = min(hi, self._n_rows)
        if lo >= real_hi:  # pure padding block
            return np.zeros((hi - lo, width), np.float64)
        block = np.ascontiguousarray(src[lo:real_hi], np.float64)
        if hi > real_hi:
            block = np.concatenate(
                [block, np.zeros((hi - real_hi, width), np.float64)])
        return block


def _classify_results(dirpath, names, n_in, n_out, results):
    """(status, X, T) in listing order from fresh read results, or None
    when any file's diagnostics are non-replayable (the corpus is then
    not residency-capable -- correctness first)."""
    status, rows_x, rows_t = [], [], []
    for idx, name in enumerate(names):
        vec_in, vec_out, diags = results[idx]
        st = _classify(dirpath, name, vec_in, vec_out, diags, n_in, n_out)
        if st is None:
            return None
        if st is _LOADED:
            status.append(len(rows_x))
            rows_x.append(np.ascontiguousarray(vec_in[:n_in], np.float64))
            rows_t.append(np.ascontiguousarray(vec_out[:n_out], np.float64))
        else:
            status.append(st)
    if not rows_x:
        return status, None, None
    return status, np.stack(rows_x), np.stack(rows_t)


def load_resident(dirpath: str, names: list[str], n_in: int,
                  n_out: int, header: str = "TRAINING",
                  prefer_mmap: bool = False):
    """Load a corpus ONCE in listing order for device residency.

    Pack-cache fast path first (mmap, no file walk); a cold load reads
    every file under the flock build guard, classifies the per-file
    diagnostics into replayable status codes, and writes the pack for
    the next run.  Returns a :class:`ResidentCorpus`, or None when the
    dir has a file with non-replayable diagnostics (the caller falls
    back to the per-epoch ``load_ordered`` path, which replays captured
    diagnostics verbatim).  Emits NO console output of its own beyond a
    dbg summary -- the per-epoch skip diagnostics are reconstructed by
    ``epoch_events`` each epoch, exactly like a warm pack load.

    ``prefer_mmap=True`` (the multi-process resident path) swaps a cold
    load's in-memory rows for the freshly written pack's memmaps, so a
    rank that had to build the pack still serves its device shard feeds
    from pack pages instead of a full host copy.
    """
    if n_in <= 0 or n_out <= 0:
        return None
    t0 = time.perf_counter()
    got = None
    if cache_enabled():
        got = _try_load_pack(dirpath, names, n_in, n_out)
    if got is None:
        with _pack_build_lock(dirpath) as locked:
            if locked and cache_enabled():
                got = _try_load_pack(dirpath, names, n_in, n_out)
            if got is None:
                stats = _stat_listing(dirpath, names)
                results, _mode = _read_results(dirpath, names, n_in, n_out)
                classified = _classify_results(dirpath, names, n_in, n_out,
                                               results)
                if classified is None:
                    nn_dbg("resident corpus: non-replayable diagnostics; "
                           "falling back to per-epoch loads\n")
                    return None
                if cache_enabled() and stats is not None:
                    if (_save_pack(dirpath, names, n_in, n_out, results,
                                   stats) and prefer_mmap):
                        reloaded = _try_load_pack(dirpath, names,
                                                  n_in, n_out)
                        if reloaded is not None:
                            classified = reloaded
                got = classified
    status, X, T = got
    rc = ResidentCorpus(dirpath, names, status, X, T, header=header)
    nn_dbg(f"resident corpus: {len(names)} file(s), {rc.n_rows} row(s) "
           f"staged once in {time.perf_counter() - t0:.3f}s\n")
    return rc


class LoadHandle:
    """A corpus load running on a background thread.  Console output is
    captured in the loader thread and replayed by :meth:`result` on the
    caller's thread, so the stream is byte-identical to a foreground
    load and never interleaves with the caller's own output."""

    def __init__(self, fn):
        self._box: dict = {}
        self._out: list = []

        def run():
            try:
                with nn_log.capture(into=self._out):
                    self._box["r"] = fn()
            except BaseException as exc:  # re-raised in result()
                self._box["e"] = exc

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="hpnn-corpus-load")
        self._thread.start()

    def result(self):
        self._thread.join()
        nn_log.replay(self._out)
        if "e" in self._box:
            raise self._box["e"]
        return self._box["r"]


def load_ordered_async(dirpath: str, names: list[str], order: list[int],
                       header: str, n_in: int, n_out: int) -> LoadHandle:
    """:func:`load_ordered` on a background thread; the caller overlaps
    device warmup with the load and joins via ``handle.result()``."""
    return LoadHandle(lambda: load_ordered(dirpath, names, order, header,
                                           n_in, n_out))


def prefetch_pack_async(dirpath: str, n_in: int,
                        n_out: int) -> threading.Thread | None:
    """Build ``dirpath``'s pack in the background if it is missing or
    stale -- silent (all console output discarded), best-effort, daemon.
    ``api.train_kernel`` points this at the test dir while the training
    epoch runs on device, so the subsequent ``run_nn`` warm-loads.
    Returns the thread (tests join it) or None when caching is off."""
    if not cache_enabled() or n_in <= 0 or n_out <= 0:
        return None

    def run():
        try:
            names = samples.list_sample_dir(dirpath)
            if not names:
                return
            if _try_load_pack(dirpath, names, n_in, n_out,
                              probe_only=True):
                return  # already warm
            with nn_log.capture():  # a prefetch never prints
                with _pack_build_lock(dirpath):
                    # the build may have raced a foreground loader (or
                    # another process): once the lock is ours, a valid
                    # pack means the winner already did the work
                    if _try_load_pack(dirpath, names, n_in, n_out,
                                      probe_only=True):
                        return
                    stats = _stat_listing(dirpath, names)
                    results, _ = _read_results(dirpath, names, n_in, n_out)
                    _save_pack(dirpath, names, n_in, n_out, results, stats)
        except Exception:
            pass  # prefetch is an optimization, never fatal

    t = threading.Thread(target=run, daemon=True,
                         name="hpnn-corpus-prefetch")
    t.start()
    return t
