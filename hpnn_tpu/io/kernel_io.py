"""Text kernel checkpoint format, byte-compatible with the reference.

Writer mirrors ``ann_dump`` (``/root/reference/src/ann.c:770-857``):

    [name] <name>
    [param] <n_in> <h1> ... <n_out>
    [input] <n_in>
    [hidden 1] <N>
    [neuron 1] <M>
    <w> <w> ... <w>          (M values at %17.15f, space separated)
    ...
    [output] <N>
    [neuron 1] <M>
    ...

Reader mirrors ``ann_load`` (``/root/reference/src/ann.c:206-631``) at the
control-flow level (round-5 rework, oracle-verified):

* the ``[param]`` line fixes the topology; weights are calloc'd ZERO and a
  ``[hidden i]``/``[output]`` section that never appears simply leaves its
  layer at zero (the reference loads such files successfully);
* each phase rewinds and re-scans the whole file, so section order is free;
* weight VALUES parse with raw strtod semantics from the one line after
  each ``[neuron j]`` header -- failed conversions read 0.0, short lines
  zero-fill, and the value loop shares samples.py's simulated getline
  buffer (stale bytes from earlier lines are reachable, like the C);
* a neuron may declare FEWER inputs than the layer width: the reference
  writes its values at the per-neuron stride (``_2D_IDX(n_par,jdx,kdx)``,
  ann.c:441), producing the same overlapped flat layout here;
* error messages and their ``->`` location lines are the reference's exact
  strings.  Paths where the reference runs into undefined behavior (a
  hidden index one past the array, an output neuron stride overflowing the
  layer allocation) fail silently instead (documented deviation).
"""

from __future__ import annotations

from typing import IO

import numpy as np

from ..models.kernel import Kernel
from ..utils.nn_log import nn_error
from .samples import _GetlineSim, _is_digit, _skip_blank, _strtod


def format_weight(v: float) -> str:
    """C's %17.15f."""
    return f"{v:17.15f}"


def dump_kernel(kernel: Kernel, fp: IO[str]) -> None:
    """Write the reference text format (ann_dump, ann.c:770-857)."""
    if kernel is None:
        nn_error("CAN'T SAVE KERNEL! kernel=NULL\n")
        return
    w = kernel.weights
    fp.write(f"[name] {kernel.name}\n")
    fp.write("[param] " + " ".join(str(p) for p in kernel.params) + "\n")
    fp.write(f"[input] {kernel.n_inputs}\n")
    for idx, mat in enumerate(w[:-1]):
        n, m = mat.shape
        fp.write(f"[hidden {idx + 1}] {n}\n")
        _dump_neurons(fp, mat)
    n, m = w[-1].shape
    fp.write(f"[output] {n}\n")
    _dump_neurons(fp, w[-1])


def _dump_neurons(fp: IO[str], mat: np.ndarray) -> None:
    n, m = mat.shape
    for j in range(n):
        fp.write(f"[neuron {j + 1}] {m}\n")
        row = mat[j]
        fp.write(" ".join(format_weight(float(v)) for v in row))
        fp.write("\n")


def dumps_kernel(kernel: Kernel) -> str:
    """The reference text format as one string (what a kernel file's
    bytes will be); the checkpoint fingerprint hashes exactly this."""
    import io

    buf = io.StringIO()
    dump_kernel(kernel, buf)
    return buf.getvalue()


def encode_kernel_text(text: str) -> bytes:
    """Kernel text -> file bytes.  latin-1 keeps byte parity with the
    reference's fprintf (a name loaded from a kernel file is latin-1-
    decoded raw bytes, so this is the identity on the round trip); a
    name with characters above U+00FF (reachable via a utf-8 conf)
    falls back to utf-8 instead of crashing -- those bytes re-decode
    latin-1 as mojibake but round-trip stably, like the C would treat
    any foreign byte sequence."""
    try:
        return text.encode("latin-1")
    except UnicodeEncodeError:
        return text.encode("utf-8")


def dump_kernel_to_path(kernel: Kernel, path: str) -> None:
    """Crash-safe kernel write: the full text is staged to a temp file,
    fsync'd, then renamed over ``path`` (io.atomic) -- a crash mid-dump
    can no longer truncate an existing ``kernel.opt``.  Shared with the
    checkpoint snapshot writer (hpnn_tpu/ckpt)."""
    from .atomic import atomic_write_bytes

    atomic_write_bytes(path, encode_kernel_text(dumps_kernel(kernel)))


def _i32(v: int) -> int:
    """printf %i of a UINT: the reference renders counts through %i, so
    4294967294 prints as -2 in its error messages."""
    return v - 2**32 if v >= 2**31 else v


# Largest layer weight count allocated densely.  np.zeros calloc's, so
# like the reference's calloc + Linux overcommit the untouched pages cost
# nothing -- a dense allocation is correct (and cheap) far past any real
# workload.  Only counts at/after 2^31 (16 GiB of doubles, where the
# reference's own (UINT) index arithmetic is deep in overflow territory)
# fall back to _SparseFlat.  The old 2^20 bound silently refused real
# kernels, e.g. a 784x1338 hidden layer (ADVICE high).
_DENSE_MAX = 1 << 31


class _SparseFlat:
    """Stand-in for a layer whose claimed size exceeds any real workload:
    the reference calloc's it anyway (Linux overcommit succeeds untouched)
    and only ever errors out of such files through the normal scan checks,
    so the scan must RUN, not bail early.  Writes are kept sparse; a load
    that would actually COMPLETE with one of these (needs billions of
    [neuron] blocks in the file -- unreachable) fails at the end."""

    def __init__(self, size: int):
        self.size = size
        self.shape = (size,)
        self.vals: dict[int, float] = {}

    def __setitem__(self, i: int, v: float) -> None:
        self.vals[i] = v


def _uint(s: str, pos: int) -> tuple[int, int]:
    """GET_UINT (common.h:269-271): ``(UINT)strtoull(...)`` -- leading C
    whitespace skipped, an optional sign (a negative value NEGATES,
    wrapping mod 2^64), 64-bit saturation on overflow, then the macro's
    (UINT) cast truncates to 32 bits.  No digits -> (0, pos)."""
    p = pos
    while p < len(s) and s[p] in " \t\n\r\v\f":
        p += 1
    neg = False
    if p < len(s) and s[p] in "+-":
        neg = s[p] == "-"
        p += 1
    j = p
    while j < len(s) and _is_digit(s[j]):
        j += 1
    if j == p:
        return 0, pos
    v = min(int(s[p:j]), 2**64 - 1)
    if neg:
        v = (2**64 - v) % 2**64
    return v & 0xFFFFFFFF, j


def _scan_to_digit(line: str, pos: int) -> int:
    """``while(!ISDIGIT(*ptr) && *ptr!='\\n' && *ptr!='\\0') ptr++`` --
    returns the position of the first digit, or of the stopper."""
    while (pos < len(line) and line[pos] not in "\n\0"
           and not _is_digit(line[pos])):
        pos += 1
    return pos


def _at_digit(line: str, pos: int) -> bool:
    return pos < len(line) and _is_digit(line[pos])


def _read_weight_row(sim: _GetlineSim, flat: np.ndarray, stride: int,
                     j: int, n_par: int) -> bool:
    """The reference's weight loop (ann.c:437-445): n_par GET_DOUBLEs from
    the just-read line's buffer, written at the PER-NEURON stride
    ``n_par*j + k`` into the layer's flat calloc'd array.  False when an
    index would leave the allocation (reference UB; silent fail)."""
    pos = _skip_blank(sim.buf, 0)
    for k in range(n_par):
        if pos < len(sim.buf):
            v, end = _strtod(sim.buf, pos)
            pos = _skip_blank(sim.buf, min(end + 1, len(sim.buf)))
        else:
            v = 0.0  # past every written byte: malloc garbage in C
        i = stride * j + k
        if i >= flat.shape[0]:
            return False
        flat[i] = v
    return True


def _load_neuron_block(sim: _GetlineSim, flat: np.ndarray, j: int,
                       n_inputs: int, where: str,
                       check_inputs: bool) -> bool | None:
    """One ``[neuron j]`` header + weights line (ann.c:400-450 hidden /
    494-534 output).  ``where`` renders the reference's location line;
    ``check_inputs`` is True only on the hidden path (the output path has
    no n_par>n_inputs guard -- overflow there is reference UB, silent
    fail).  Returns True, or None on a (printed) error, False on UB."""
    line = sim.cline()
    kpos = line.find("[neuron")
    if kpos < 0:
        nn_error("kernel read: neuron definition missing!\n")
        nn_error(f"-> {where}, neuron {j + 1}\n")
        return None
    q = _scan_to_digit(line, kpos)
    if not _at_digit(line, q):
        nn_error("kernel read: missing neuron number!\n")
        nn_error(f"-> {where}, neuron {j + 1}\n")
        return None
    num, end = _uint(line, q)
    if num < 1:
        nn_error("kernel read: neuron number<1\n")
        nn_error(f"-> {where}, neuron {j + 1}\n")
        return None
    q = _skip_blank(line, min(end + 1, len(line)))
    if not _at_digit(line, q):
        nn_error("kernel read: neuron has no input number!\n")
        nn_error(f"-> {where}, neuron {j + 1}\n")
        return None
    n_par, _ = _uint(line, q)
    if n_par < 1:
        nn_error("kernel read: neuron has less that 1 input!\n")
        nn_error(f"-> {where}, neuron {j + 1}\n")
        return None
    if check_inputs and n_par > n_inputs:
        nn_error("kernel read: neuron inconsistent input number!\n")
        nn_error(f"-> n_input={_i32(n_par)} (expected {_i32(n_inputs)})!\n")
        nn_error(f"-> {where}, neuron {j + 1}\n")
        return None
    sim.readline()  # weights line
    if not _read_weight_row(sim, flat, n_par, j, n_par):
        return False
    sim.readline()
    return True


def load_kernel(path: str) -> Kernel | None:
    """Parse the text kernel format (ann_load, ann.c:206-631).

    Returns None on malformed input, with the reference's NN(ERR)
    messages; see the module docstring for the control-flow contract.
    """
    try:
        fp = open(path, "r", encoding="latin-1")
    except OSError:
        nn_error(f"Error opening kernel file: {path}\n")
        return None
    with fp:
        raw = fp.readlines()
    sim = _GetlineSim(raw)
    sim.readline()  # line 1: name
    if "[name]" not in sim.cline():
        nn_error("kernel file should start with [name] keyword!\n")
        return None
    after = sim.cline().split("[name]", 1)[1]
    name = after[_skip_blank(after, 0):].split("\n", 1)[0]

    # --- [param] phase (ann.c:276-334): scan from the name line on -----
    n_in = n_out = n_hid = 0
    hid_out: list[int] = []
    while True:
        line = sim.cline()
        if "[param]" in line:
            q = _scan_to_digit(line, 0)
            if not _at_digit(line, q):
                nn_error("kernel read: malformed parameter line!\n")
                return None
            # counting pass (GET_UINT until newline/NUL)
            n_par = 0
            pos = q
            while True:
                _, end = _uint(line, pos)
                if end < len(line) and line[end] in "\n\0":
                    pos = end
                else:
                    pos = min(end + 1, len(line))
                pos = _skip_blank(line, pos)
                n_par += 1
                if pos >= len(line) or line[pos] in "\n\0":
                    break
            n_par -= 1
            if n_par < 2:
                nn_error("kernel read: parameter line has too few "
                         "parameters!\n")
                return None
            n_hid = n_par - 1
            # value pass: n_in then the n_par layer sizes
            pos = _scan_to_digit(line, 0)
            n_in, end = _uint(line, pos)
            pos = _skip_blank(line, min(end + 1, len(line)))
            hid_out = []
            for _ in range(n_par):
                v, end = _uint(line, pos)
                hid_out.append(v)
                pos = _skip_blank(line, min(end + 1, len(line)))
            if any(v == 0 for v in hid_out):
                nn_error("kernel read: zero in parameter line!\n")
                return None
            n_out = hid_out[-1]
            break
        sim.readline()
        if sim.feof:
            break
    if n_in == 0:
        # also the no-[param]-line case (the reference checks n_in, so a
        # zero FIRST parameter reports "missing" too -- quirk preserved)
        nn_error("kernel read: missing parameter line!\n")
        return None
    if n_out < 1:
        nn_error("kernel read: wrong parameter n_output<1!\n")
        return None
    if n_hid < 1:
        nn_error("kernel read: wrong parameter n_hiddens<1!\n")
        return None

    dims = [n_in] + hid_out  # n_layers = n_hid hidden + 1 output
    flats = [np.zeros(dims[i + 1] * dims[i], np.float64)
             if dims[i + 1] * dims[i] < _DENSE_MAX
             else _SparseFlat(dims[i + 1] * dims[i])  # overcommit analog
             for i in range(len(dims) - 1)]

    # --- [hidden i] phase (ann.c:343-459): rewind, re-scan everything --
    sim.rewind()
    while True:
        line = sim.cline()
        kpos = line.find("[hidden")
        if kpos >= 0:
            q = _scan_to_digit(line, kpos)
            if not _at_digit(line, q):
                nn_error("kernel read: malformed hidden layer definition\n")
                return None
            idx, end = _uint(line, q)
            if idx == 0:
                nn_error("kernel read: wrong hidden layer index (=0)!\n")
                return None
            idx -= 1
            if idx > n_hid:
                nn_error("kernel read: wrong hidden layer index "
                         "(> n_hiddens)!\n")
                return None
            if idx >= n_hid:
                return None  # reference indexes hiddens[n_hid]: UB
            q = _scan_to_digit(line, min(end + 1, len(line)))
            jdx, _ = _uint(line, q)
            if jdx != dims[idx + 1]:
                nn_error("kernel read: inconsistent neuron number!\n")
                nn_error(f"-> layer {idx + 1} n_neurons={_i32(jdx)} "
                         f"(expected {_i32(dims[idx + 1])})\n")
                return None
            sim.readline()
            for j in range(dims[idx + 1]):
                r = _load_neuron_block(sim, flats[idx], j, dims[idx],
                                       f"hidden layer {idx + 1}",
                                       check_inputs=True)
                if r is not True:
                    return None
        else:
            sim.readline()
        if sim.feof:
            break

    # --- [output] phase (ann.c:458-546): rewind, re-scan ---------------
    sim.rewind()
    while True:
        line = sim.cline()
        kpos = line.find("[output]")
        if kpos >= 0:
            q = _scan_to_digit(line, kpos)
            if not _at_digit(line, q):
                nn_error("kernel read: malformed output layer definition\n")
                return None
            idx, _ = _uint(line, q)
            if idx != dims[-1]:
                nn_error("kernel read: inconsistent neuron number for "
                         "output!\n")
                nn_error(f"-> n_neurons={_i32(idx)} "
                     f"(expected {_i32(dims[-1])})\n")
                return None
            sim.readline()
            for j in range(dims[-1]):
                r = _load_neuron_block(sim, flats[-1], j, dims[-2],
                                       "output layer", check_inputs=False)
                if r is not True:
                    return None
        sim.readline()
        if sim.feof:
            break

    for i, f in enumerate(flats):
        if isinstance(f, _SparseFlat):
            # completing a load at this size would need a >=16 GiB dense
            # array (and a correspondingly impossible file); the reference
            # would be deep in overcommitted memory here -- fail with a
            # diagnostic naming the layer (documented deviation; the old
            # bare `return None` looked like an unreadable file)
            nn_error(f"kernel read: layer {i + 1} weight count "
                     f"{f.size} too large to allocate!\n")
            return None
    weights = [flats[i].reshape(dims[i + 1], dims[i])
               for i in range(len(dims) - 1)]
    return Kernel(name=name, weights=weights)
