"""Text kernel checkpoint format, byte-compatible with the reference.

Writer mirrors ``ann_dump`` (``/root/reference/src/ann.c:770-857``):

    [name] <name>
    [param] <n_in> <h1> ... <n_out>
    [input] <n_in>
    [hidden 1] <N>
    [neuron 1] <M>
    <w> <w> ... <w>          (M values at %17.15f, space separated)
    ...
    [output] <N>
    [neuron 1] <M>
    ...

Reader mirrors ``ann_load`` (``/root/reference/src/ann.c:206-631``): the
``[param]`` line fixes the topology, then ``[hidden i]``/``[output]`` sections
each carry N ``[neuron j]`` blocks of M weights.  The reference requires the
file to start with ``[name]`` (ann.c:260-264) and validates every count; we do
the same so malformed files fail identically.
"""

from __future__ import annotations

from typing import IO, Iterator

import numpy as np

from ..models.kernel import Kernel
from ..utils.nn_log import nn_error


def format_weight(v: float) -> str:
    """C's %17.15f."""
    return f"{v:17.15f}"


def dump_kernel(kernel: Kernel, fp: IO[str]) -> None:
    """Write the reference text format (ann_dump, ann.c:770-857)."""
    if kernel is None:
        nn_error("CAN'T SAVE KERNEL! kernel=NULL\n")
        return
    w = kernel.weights
    fp.write(f"[name] {kernel.name}\n")
    fp.write("[param] " + " ".join(str(p) for p in kernel.params) + "\n")
    fp.write(f"[input] {kernel.n_inputs}\n")
    for idx, mat in enumerate(w[:-1]):
        n, m = mat.shape
        fp.write(f"[hidden {idx + 1}] {n}\n")
        _dump_neurons(fp, mat)
    n, m = w[-1].shape
    fp.write(f"[output] {n}\n")
    _dump_neurons(fp, w[-1])


def _dump_neurons(fp: IO[str], mat: np.ndarray) -> None:
    n, m = mat.shape
    for j in range(n):
        fp.write(f"[neuron {j + 1}] {m}\n")
        row = mat[j]
        fp.write(" ".join(format_weight(float(v)) for v in row))
        fp.write("\n")


def dump_kernel_to_path(kernel: Kernel, path: str) -> None:
    with open(path, "w") as fp:
        dump_kernel(kernel, fp)


class _Lines:
    """Line cursor returning None at EOF."""

    def __init__(self, fp: IO[str]):
        self._it: Iterator[str] = iter(fp)

    def next(self) -> str | None:
        return next(self._it, None)


def _parse_ints(text: str) -> list[int]:
    vals = []
    for tok in text.replace("\t", " ").split():
        if tok.lstrip("-").isdigit():
            vals.append(int(tok))
        else:
            break
    return vals


def load_kernel(path: str) -> Kernel | None:
    """Parse the text kernel format (ann_load, ann.c:206-631).

    Returns None on malformed input, with the reference's NN(ERR) messages.
    """
    try:
        fp = open(path, "r")
    except OSError:
        nn_error(f"Error opening kernel file: {path}\n")
        return None
    with fp:
        lines = _Lines(fp)
        first = lines.next()
        if first is None or "[name]" not in first:
            nn_error("kernel file should start with [name] keyword!\n")
            return None
        name = first.split("[name]", 1)[1].strip()
        if not name:
            name = "noname"
        # find [param]
        params: list[int] | None = None
        line = first
        while line is not None:
            if "[param]" in line:
                params = _parse_ints(line.split("[param]", 1)[1])
                break
            line = lines.next()
        if not params:
            nn_error("kernel read: missing parameter line!\n")
            return None
        if len(params) < 3:
            nn_error("kernel read: parameter line has too few parameters!\n")
            return None
        if any(p == 0 for p in params):
            nn_error("kernel read: zero in parameter line!\n")
            return None
        dims = params
        n_layers = len(dims) - 1
        weights: list[np.ndarray | None] = [None] * n_layers

        line = lines.next()
        while line is not None:
            stripped = line
            if "[hidden" in stripped and "]" in stripped:
                head = stripped.split("[hidden", 1)[1]
                idx_txt, rest = head.split("]", 1)
                if not idx_txt.strip().isdigit():
                    nn_error("kernel read: wrong hidden layer parameters!\n")
                    return None
                layer = int(idx_txt.strip()) - 1
                n = _parse_ints(rest)
                if layer < 0 or layer >= n_layers - 1 or not n or n[0] != dims[layer + 1]:
                    nn_error("kernel read: wrong hidden layer parameters!\n")
                    return None
                mat = _read_layer(lines, dims[layer + 1], dims[layer])
                if mat is None:
                    return None
                weights[layer] = mat
            elif "[output]" in stripped:
                n = _parse_ints(stripped.split("[output]", 1)[1])
                if not n or n[0] != dims[-1]:
                    nn_error("kernel read: wrong output parameters!\n")
                    return None
                mat = _read_layer(lines, dims[-1], dims[-2])
                if mat is None:
                    return None
                weights[-1] = mat
            line = lines.next()

        if any(w is None for w in weights):
            nn_error("kernel read: missing layer weights!\n")
            return None
        return Kernel(name=name, weights=[np.asarray(w, dtype=np.float64) for w in weights])


def _read_layer(lines: _Lines, n: int, m: int) -> np.ndarray | None:
    """Read N [neuron j] blocks of M doubles each."""
    mat = np.empty((n, m), dtype=np.float64)
    for j in range(n):
        line = lines.next()
        while line is not None and line.strip() == "":
            line = lines.next()
        if line is None or "[neuron" not in line or "]" not in line:
            nn_error("kernel read: missing neuron line!\n")
            return None
        head = line.split("[neuron", 1)[1]
        _, rest = head.split("]", 1)
        cnt = _parse_ints(rest)
        if not cnt or cnt[0] != m:
            nn_error("kernel read: wrong neuron parameters!\n")
            return None
        # read m doubles from subsequent lines
        vals: list[float] = []
        while len(vals) < m:
            line = lines.next()
            if line is None:
                nn_error("kernel read: missing weight values!\n")
                return None
            for tok in line.split():
                try:
                    vals.append(float(tok))
                except ValueError:
                    nn_error("kernel read: bad weight value!\n")
                    return None
                if len(vals) == m:
                    break
        mat[j] = vals
    return mat
