from .conf import NNConf, dump_conf, load_conf, parse_conf
from .corpus import (
    load_ordered,
    load_ordered_async,
    pack_path,
    prefetch_pack_async,
)
from .kernel_io import dump_kernel, dump_kernel_to_path, load_kernel
from .samples import list_sample_dir, read_sample

__all__ = [
    "NNConf",
    "parse_conf",
    "load_conf",
    "dump_conf",
    "load_kernel",
    "dump_kernel",
    "dump_kernel_to_path",
    "read_sample",
    "list_sample_dir",
    "load_ordered",
    "load_ordered_async",
    "prefetch_pack_async",
    "pack_path",
]
