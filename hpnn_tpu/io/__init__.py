from .conf import NNConf, dump_conf, load_conf, parse_conf
from .kernel_io import dump_kernel, dump_kernel_to_path, load_kernel
from .samples import list_sample_dir, read_sample

__all__ = [
    "NNConf",
    "parse_conf",
    "load_conf",
    "dump_conf",
    "load_kernel",
    "dump_kernel",
    "dump_kernel_to_path",
    "read_sample",
    "list_sample_dir",
]
