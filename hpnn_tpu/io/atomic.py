"""Crash-safe file writes: tmp + fsync + rename, shared by every
persistent artifact the framework emits.

The reference dumps its kernel with a plain ``fopen``/``fprintf`` pass
(``/root/reference/tests/train_nn.c:224-243``) -- a crash mid-write
leaves a truncated ``kernel.opt`` that ``ann_load`` then rejects (or
worse, half-parses into zero weights).  Every writer here goes through
the POSIX durable-replace sequence instead:

1. write the full payload to a temp file **in the destination
   directory** (rename is only atomic within one filesystem);
2. flush + ``fsync`` the temp file so the bytes are on disk before the
   name flip;
3. ``os.replace`` onto the destination (atomic on POSIX: readers see
   the old complete file or the new complete file, never a mix);
4. best-effort ``fsync`` of the parent directory so the rename itself
   survives a power cut (skipped silently where the FS refuses
   directory fsync, e.g. some network mounts).

Used by ``io.kernel_io.dump_kernel_to_path`` (every ``kernel.opt`` /
``kernel.tmp`` write) and the checkpoint subsystem's snapshot/manifest
writers (``hpnn_tpu/ckpt``).

Fault injection (ISSUE 14): every write consults the chaos layer's io
domain (``HPNN_FAULT`` rules like ``enospc@manifest:times=1`` or
``bitflip@state.npz``) through :func:`io_fault_hook`, so the snapshot
retry / verified-resume machinery is testable without a failing disk.
The hook is zero-cost when chaos is unarmed -- and the serve package
(where the chaos module lives) is never even imported unless
``HPNN_FAULT`` is set or a test armed it programmatically.
"""

from __future__ import annotations

import contextlib
import os
import sys
import tempfile

_CHAOS_MOD = __name__.rsplit(".", 2)[0] + ".serve.mesh.chaos"


def io_fault_hook(path: str, data: bytes) -> bytes:
    """Consult the chaos io domain for one pending durable write:
    raises (enospc/eio), delays (latency), or returns the payload --
    possibly corrupted (torn/bitflip) -- that should hit the disk.
    A no-import no-op while chaos is unarmed."""
    chaos = sys.modules.get(_CHAOS_MOD)
    if chaos is None:
        if not os.environ.get("HPNN_FAULT"):
            return data  # unarmed: never pull in the serve stack
        import importlib

        chaos = importlib.import_module(_CHAOS_MOD)
    rule = chaos.pick_io(path)
    if rule is None:
        return data
    return chaos.apply_io_fault(rule, path, data)


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a DIRECTORY so a just-renamed entry survives
    power loss; silently skipped where the FS does not support it."""
    with contextlib.suppress(OSError):
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Durably replace ``path`` with ``data`` (tmp + fsync + rename)."""
    data = io_fault_hook(path, data)
    dirpath = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix="." + os.path.basename(path) + ".",
                               suffix=".tmp", dir=dirpath)
    try:
        with os.fdopen(fd, "wb") as fp:
            fp.write(data)
            fp.flush()
            os.fsync(fp.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    fsync_dir(dirpath)


def atomic_write_text(path: str, text: str,
                      encoding: str = "utf-8") -> None:
    atomic_write_bytes(path, text.encode(encoding))
