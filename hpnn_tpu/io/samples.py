"""Sample-file I/O and directory listing.

A sample file is text (``_NN(read,sample)``,
``/root/reference/src/libhpnn.c:1070-1145``):

    [input] N
    v1 v2 ... vN
    [output] M
    t1 t2 ... tM

The reference reads all N values from the single line following the header
(libhpnn.c:1102-1111); we additionally accept values spanning several lines
(documented deviation -- strictly more permissive, every reference-valid file
parses identically).  Directory listing skips dotfiles (``libhpnn.c:1194-1198``)
and preserves the OS readdir order, exactly like the reference -- required for
the end-to-end training parity proven in tests/test_reference_parity.py (see
list_sample_dir's docstring).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from ..utils.nn_log import nn_error


def read_sample(path: str) -> tuple[np.ndarray | None, np.ndarray | None]:
    """Parse one sample file; (None, None) on failure, as the reference."""
    try:
        fp = open(path, "r")
    except OSError:
        return None, None
    vec_in: np.ndarray | None = None
    vec_out: np.ndarray | None = None
    with fp:
        lines = fp.readlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        if "[input" in line:
            n, vals, i = _read_vector(lines, i, "[input", path, "input")
            if vals is None:
                return None, None
            vec_in = vals
            continue
        if "[output" in line:
            n, vals, i = _read_vector(lines, i, "[output", path, "output")
            if vals is None:
                return None, None
            vec_out = vals
            continue
        i += 1
    return vec_in, vec_out


def _read_vector(lines, i, key, path, what):
    rest = lines[i].split(key, 1)[1]
    if rest[:1] == "]":
        rest = rest[1:]
    rest = rest.strip()
    if not rest or not rest.split()[0].isdigit():
        nn_error(f"sample {path} {what} read failed!\n")
        return None, None, i
    n = int(rest.split()[0])
    if n == 0:
        # the reference prints "input read failed" even for the output count
        # (copy-paste quirk at libhpnn.c:1122-1125) -- grammar is API, keep it
        nn_error(f"sample {path} input read failed!\n")
        return None, None, i
    vals: list[float] = []
    i += 1
    while len(vals) < n and i < len(lines):
        for tok in lines[i].split():
            try:
                vals.append(float(tok))
            except ValueError:
                nn_error(f"sample {path} {what} read failed!\n")
                return None, None, i
            if len(vals) == n:
                break
        i += 1
    if len(vals) < n:
        nn_error(f"sample {path} {what} read failed!\n")
        return None, None, i
    return n, np.asarray(vals, dtype=np.float64), i


# --- native fast path -------------------------------------------------------
# native/sample_loader.c parses well-formed files ~10x faster than the
# Python token loop (the reference's own loader is C, libhpnn.c:1070-1145;
# at MNIST scale -- 60k files -- parsing dominates driver startup).  Any
# anomaly makes the C side DECLINE and the Python parser re-read the file,
# so diagnostics and edge-case behavior stay byte-identical.

_native_lib = None


def _native():
    global _native_lib
    if _native_lib is not None:
        return _native_lib or None
    if os.environ.get("HPNN_NO_NATIVE_IO"):
        _native_lib = False
        return None
    path = os.environ.get("HPNN_IO_LIB") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native", "libhpnn_io.so")
    try:
        lib = ctypes.CDLL(path)
        lib.hpnn_read_sample.restype = ctypes.c_int
        lib.hpnn_read_sample.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        _native_lib = lib
    except OSError:
        _native_lib = False
        return None
    return _native_lib


def read_sample_fast(path: str, n_in_hint: int, n_out_hint: int):
    """read_sample with a native fast path sized by the expected dims.

    Returns exactly what :func:`read_sample` would -- the C parser only
    serves files it can parse cleanly within the hinted capacities and
    declines everything else back to the Python parser.
    """
    lib = _native()
    if lib is None or n_in_hint <= 0 or n_out_hint <= 0:
        return read_sample(path)
    in_buf = np.empty(n_in_hint, np.float64)
    out_buf = np.empty(n_out_hint, np.float64)
    n_in = ctypes.c_int(0)
    n_out = ctypes.c_int(0)
    rc = lib.hpnn_read_sample(
        path.encode(),
        in_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_in_hint, ctypes.byref(n_in),
        out_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_out_hint, ctypes.byref(n_out))
    if rc == -1:
        return None, None  # unopenable: same answer, no second syscall
    if rc != 0:
        return read_sample(path)  # decline: Python re-reads w/ diagnostics
    return in_buf[:n_in.value], out_buf[:n_out.value]


def list_sample_dir(dirpath: str) -> list[str] | None:
    """File names (not paths) in dirpath, dotfiles skipped, READDIR order.

    The reference walks readdir order (libhpnn.c:1190-1214) and applies the
    seeded shuffle on top of it; os.listdir returns the same readdir order,
    so keeping it unsorted makes the shuffled sequence -- and therefore the
    whole training trajectory -- identical to the reference's on the same
    filesystem (verified against the compiled reference in
    tests/test_reference_parity.py).  Note readdir order is filesystem-
    dependent, so runs are reproducible per-machine, exactly like the
    reference.
    """
    try:
        names = os.listdir(dirpath)
    except OSError:
        return None
    return [n for n in names if not n.startswith(".")
            and os.path.isfile(os.path.join(dirpath, n))]


# NOTE: bulk loading in shuffle order lives in hpnn_tpu.api._load_ordered,
# which owns the driver's skip/diagnostic semantics (one loader, no drift).
