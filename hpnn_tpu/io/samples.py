"""Sample-file I/O and directory listing.

A sample file is text (``_NN(read,sample)``,
``/root/reference/src/libhpnn.c:1070-1145``):

    [input] N
    v1 v2 ... vN
    [output] M
    t1 t2 ... tM

The reference reads all N values from the SINGLE line following the header
(libhpnn.c:1102-1111) with raw ``strtod`` semantics -- a token strtod cannot
convert yields 0.0 and advances one character (``GET_DOUBLE`` +
``ptr=ptr2+1``, common.h:272-274), so short lines zero-fill and non-numeric
tokens read as 0.0 rather than failing; the only read failures are
unopenable/empty files and bad/zero section counts.  This parser replicates
that behavior exactly (round-5 oracle sweep; the old version was stricter
AND accepted multi-line values -- both divergences).  One deliberate
deviation remains at the DRIVER level: a file whose section count is
smaller than the kernel's dimension makes the reference copy past its
allocation (libhpnn.c:1243, undefined behavior) -- the corpus loader
(``io.corpus``) skips such files with a diagnostic instead.
Directory listing skips dotfiles (``libhpnn.c:1194-1198``)
and preserves the OS readdir order, exactly like the reference -- required for
the end-to-end training parity proven in tests/test_reference_parity.py (see
list_sample_dir's docstring).
"""

from __future__ import annotations

import ctypes
import os
import re
import threading

import numpy as np

from ..utils.nn_log import nn_error, nn_warn

# C strtod's accepted prefix: hex floats first (else the decimal branch
# would stop at the "0" of "0x1f"), then decimal w/ optional exponent
# (an incomplete exponent backtracks to the mantissa, like strtod), then
# inf/infinity and nan(chars), all case-insensitive.
_STRTOD_RE = re.compile(
    r"[+-]?(?:"
    r"0[xX](?:[0-9a-fA-F]+(?:\.[0-9a-fA-F]*)?|\.[0-9a-fA-F]+)"
    r"(?:[pP][+-]?\d+)?"
    r"|(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][+-]?\d+)?"
    r"|[iI][nN][fF](?:[iI][nN][iI][tT][yY])?"
    r"|[nN][aA][nN](?:\([0-9A-Za-z_]*\))?"
    r")")

# a section count past any real workload (MNIST 784, XRD 851): the
# reference ALLOCs the claimed count and error-exits the process on OOM
# (common.h:161-167); aborting a 60k-file run on one corrupt header is
# hostile, so counts beyond this are a read failure + skip instead
# (documented deviation)
_MAX_COUNT = 1 << 20


_C_SPACE = " \t\n\r\v\f"  # C isspace set (C locale)


def _is_digit(ch: str) -> bool:
    """C ISDIGIT: ASCII '0'-'9' ONLY.  str.isdigit also accepts Unicode
    digits -- the latin-1 superscripts 0xB2/0xB3/0xB9 in a corrupt file
    would pass an .isdigit() gate and then raise ValueError from int()
    instead of taking the graceful error path (ADVICE medium)."""
    return "0" <= ch <= "9"


def _strtod(s: str, pos: int) -> tuple[float, int]:
    """GET_DOUBLE (common.h:272-274): strtod skips leading C whitespace
    (which can include a newline) then parses its longest prefix at
    ``pos``; no conversion -> (0.0, pos) (strtod sets endptr=nptr).
    A NUL in the simulated buffer is never crossed -- it terminates the
    C string strtod sees."""
    p = pos
    while p < len(s) and s[p] in _C_SPACE:
        p += 1
    if p < len(s) and s[p] == "\0":
        return 0.0, pos
    m = _STRTOD_RE.match(s, p)
    if m is None:
        return 0.0, pos
    tok = m.group(0)
    low = tok.lstrip("+-").lower()
    if low.startswith("nan"):
        v = float("nan")
    elif low.startswith("inf"):
        v = float("-inf") if tok[0] == "-" else float("inf")
    elif low.startswith("0x"):
        v = float.fromhex(tok)
    else:
        v = float(tok)
    return v, m.end()


def _skip_blank(s: str, pos: int) -> int:
    """SKIP_BLANK (common.h:250-251): advance over non-ISGRAPH chars,
    stopping at newline, NUL, or end.  ISGRAPH is the C-locale set
    (0x21-0x7E) -- bytes >0x7E are skipped as blanks, exactly like the
    reference compiled under the C locale."""
    while pos < len(s):
        ch = s[pos]
        if ch == "\n" or ch == "\0" or 0x21 <= ord(ch) <= 0x7E:
            break
        pos += 1
    return pos


def _section_count(line: str, key: str) -> int | None:
    """The reference's count parse: ``ptr += len("[input")+1`` (skipping
    one char after the keyword, whatever it is), SKIP_BLANK, ISDIGIT
    check, then strtoull's digit prefix (GET_UINT, common.h:269-271) --
    so ``[input] 4.5`` reads count 4.  None = not a digit."""
    after = line.split(key, 1)[1][1:]
    pos = _skip_blank(after, 0)
    if pos >= len(after) or not _is_digit(after[pos]):
        return None
    j = pos
    while j < len(after) and _is_digit(after[j]):
        j += 1
    # (UINT)strtoull semantics, exactly like kernel_io._uint: saturate at
    # 2^64-1, then the macro's cast truncates to 32 bits -- BEFORE the
    # driver's _MAX_COUNT range check, so the two parsers agree with the
    # reference on absurd counts (ADVICE low)
    return min(int(after[pos:j]), 2**64 - 1) & 0xFFFFFFFF


def _parse_values_line(buf: str, n: int) -> np.ndarray:
    """The reference's value loop (libhpnn.c:1102-1111): n GET_DOUBLEs
    from ONE line; after each non-final value, skip exactly one char
    (``ptr=ptr2+1``) then SKIP_BLANK.  A failed conversion yields 0.0
    and the one-char skip still advances, which is what zero-fills short
    lines and reads non-numeric tokens as 0.0.

    ``buf`` is the SIMULATED getline buffer, not just the current line:
    the one-char skip steps PAST the line's NUL terminator into stale
    bytes left by the file's earlier (longer) lines, and strtod can then
    parse those -- e.g. a '[input] 5' header overwritten by a '1 2 3'
    values line leaves ' 5' at offsets 7-8, and the reference reads
    [1,2,3,0,5] (verified against the compiled oracle).  Past the end of
    every previously written byte the C buffer holds malloc garbage;
    that region reads as zeros here (documented residual -- it is not
    reproducible even between builds of the reference)."""
    vals = np.empty(n, np.float64)
    pos = _skip_blank(buf, 0)
    for idx in range(n - 1):
        if pos >= len(buf):
            # beyond the simulated buffer every GET_DOUBLE yields 0.0 --
            # short-circuit the remaining iterations (bounded time)
            vals[idx:] = 0.0
            return vals
        v, end = _strtod(buf, pos)
        vals[idx] = v
        pos = _skip_blank(buf, min(end + 1, len(buf)))
    vals[n - 1] = _strtod(buf, pos)[0] if pos < len(buf) else 0.0
    return vals


class _GetlineSim:
    """The reference's READLINE/getline state: ONE growing buffer reused
    for every line of a file.

    * ``line`` is the C string the scanners see: the new line's bytes up
      to (and excluding) the terminator -- keyword searches must use
      :meth:`cline`, which additionally stops at any EMBEDDED NUL byte
      from the file, like strstr would.
    * ``buf`` is the full simulated buffer: the new line + an explicit
      NUL + the stale tail of earlier, longer lines -- the strtod value
      loops can walk into it (see _parse_values_line).
    * a read at EOF FAILS, leaving line and buf unchanged and setting
      ``feof``.  glibc sets the stream's EOF flag already on the read
      that RETURNS a final line with no trailing newline (verified with
      a compiled probe), so the reference's ``do{{scan;READLINE}}
      while(!feof)`` loops never scan such a line -- replicated here.
    * ``rewind`` clears feof but keeps the buffer (ann_load re-scans the
      file per section phase with the same buffer).
    """

    def __init__(self, lines: list[str]):
        self.lines = lines
        self.i = -1
        self.line = ""
        self.buf = ""
        self.feof = False

    def readline(self) -> None:
        if self.i + 1 < len(self.lines):
            self.i += 1
            new = self.lines[self.i]
            self.buf = new + "\0" + self.buf[len(new) + 1:]
            self.line = new
            if self.i == len(self.lines) - 1 and not new.endswith("\n"):
                self.feof = True
        else:
            self.feof = True

    def cline(self) -> str:
        """The C string strstr sees: up to the first embedded NUL."""
        return self.line.split("\0", 1)[0]

    def rewind(self) -> None:
        self.i = -1
        self.feof = False


def read_sample(path: str) -> tuple[np.ndarray | None, np.ndarray | None]:
    """Parse one sample file; (None, None) on failure, as the reference.

    Control flow mirrors _NN(read,sample) (libhpnn.c:1070-1145): the
    section keyword is matched anywhere in the current line, the values
    come from the next line (READLINE), and that VALUES line is then
    itself checked for the ``[output`` keyword in the same iteration.
    At EOF, getline leaves the buffer unchanged, so a header with no
    following line (re)parses the header line itself as values; a FINAL
    header line without a trailing newline is never scanned at all (the
    glibc feof timing, see _GetlineSim).  Files are decoded latin-1 so
    every byte maps to one char, like the byte-oriented reference (a
    corrupt byte reads as junk that strtod turns into 0.0, never a
    decode error).
    """
    try:
        fp = open(path, "r", encoding="latin-1")
    except OSError:
        return None, None
    with fp:
        lines = fp.readlines()
    if not lines:
        # the reference's line==NULL check (libhpnn.c:1083-1087) is dead
        # under glibc -- getline allocates even at immediate EOF, so an
        # empty file silently yields (NULL, NULL) with no message
        return None, None
    vec_in: np.ndarray | None = None
    vec_out: np.ndarray | None = None
    sim = _GetlineSim(lines)
    sim.readline()
    while True:
        cl = sim.cline()
        if "[input" in cl:
            n = _section_count(cl, "[input")
            if n is None or n == 0 or n > _MAX_COUNT:
                nn_error(f"sample {path} input read failed!\n")
                return None, None
            sim.readline()
            vec_in = _parse_values_line(sim.buf, n)
            cl = sim.cline()
        if "[output" in cl:
            n = _section_count(cl, "[output")
            if n is None or n > _MAX_COUNT:
                nn_error(f"sample {path} output read failed!\n")
                return None, None
            if n == 0:
                # the reference prints "input read failed" for a zero
                # OUTPUT count (copy-paste quirk, libhpnn.c:1122-1125)
                nn_error(f"sample {path} input read failed!\n")
                return None, None
            sim.readline()
            vec_out = _parse_values_line(sim.buf, n)
        sim.readline()
        if sim.feof:
            break
    return vec_in, vec_out


# --- native fast path -------------------------------------------------------
# native/sample_loader.c parses well-formed files ~10x faster than the
# Python token loop (the reference's own loader is C, libhpnn.c:1070-1145;
# at MNIST scale -- 60k files -- parsing dominates driver startup).  Any
# anomaly makes the C side DECLINE and the Python parser re-read the file,
# so diagnostics and edge-case behavior stay byte-identical.

_native_lib = None
_native_lock = threading.Lock()
_native_warned = False


def _native():
    global _native_lib, _native_warned
    if _native_lib is not None:
        return _native_lib or None
    with _native_lock:  # the parallel loader probes from worker threads
        if _native_lib is not None:
            return _native_lib or None
        if os.environ.get("HPNN_NO_NATIVE_IO"):
            _native_lib = False
            return None
        path = os.environ.get("HPNN_IO_LIB") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "native", "libhpnn_io.so")
        try:
            lib = ctypes.CDLL(path)
            lib.hpnn_read_sample.restype = ctypes.c_int
            lib.hpnn_read_sample.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_double), ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_double), ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
            ]
            _native_lib = lib
        except OSError as exc:
            # the fallback used to be SILENT: a deleted/unbuildable .so
            # quietly reparsed 60k files in Python at ~10x the cost.
            # Diagnose once, name the path tried, keep serving.
            _native_lib = False
            if not _native_warned:
                _native_warned = True
                nn_warn(f"native sample loader unavailable "
                        f"({path}: {exc}); parsing samples in Python\n")
            return None
    return _native_lib


def native_io_status() -> str:
    """'on' when the native fast path serves reads, 'off' otherwise
    (opt-out env or a failed library load) -- surfaced in the loader's
    load-stats line and the serve /metrics snapshot."""
    return "on" if _native() is not None else "off"


def read_sample_fast(path: str, n_in_hint: int, n_out_hint: int):
    """read_sample with a native fast path sized by the expected dims.

    Returns exactly what :func:`read_sample` would -- the C parser only
    serves files it can parse cleanly within the hinted capacities and
    declines everything else back to the Python parser.
    """
    lib = _native()
    if lib is None or n_in_hint <= 0 or n_out_hint <= 0:
        return read_sample(path)
    in_buf = np.empty(n_in_hint, np.float64)
    out_buf = np.empty(n_out_hint, np.float64)
    n_in = ctypes.c_int(0)
    n_out = ctypes.c_int(0)
    rc = lib.hpnn_read_sample(
        path.encode(),
        in_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_in_hint, ctypes.byref(n_in),
        out_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_out_hint, ctypes.byref(n_out))
    if rc == -1:
        return None, None  # unopenable: same answer, no second syscall
    if rc != 0:
        return read_sample(path)  # decline: Python re-reads w/ diagnostics
    return in_buf[:n_in.value], out_buf[:n_out.value]


def list_sample_dir(dirpath: str) -> list[str] | None:
    """File names (not paths) in dirpath, dotfiles skipped, READDIR order.

    The reference walks readdir order (libhpnn.c:1190-1214) and applies the
    seeded shuffle on top of it; os.listdir returns the same readdir order,
    so keeping it unsorted makes the shuffled sequence -- and therefore the
    whole training trajectory -- identical to the reference's on the same
    filesystem (verified against the compiled reference in
    tests/test_reference_parity.py).  Note readdir order is filesystem-
    dependent, so runs are reproducible per-machine, exactly like the
    reference.
    """
    try:
        names = os.listdir(dirpath)
    except OSError:
        return None
    return [n for n in names if not n.startswith(".")
            and os.path.isfile(os.path.join(dirpath, n))]


# NOTE: bulk loading in shuffle order lives in hpnn_tpu.io.corpus
# (parallel loader + packed corpus cache), which owns the driver's
# skip/diagnostic semantics (one loader, no drift).
