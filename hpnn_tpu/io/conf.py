"""``.conf`` run configuration parser/dumper.

Mirrors ``_NN(load,conf)`` / ``_NN(dump,conf)``
(``/root/reference/src/libhpnn.c:658-937``).  Keyword lines are recognised by
substring search anywhere in the line (STRFIND), values are cleaned by
truncating at the first space/tab/newline/'#' (STR_CLEAN, common.h:254-262).

Recognised keywords and semantics (all cited to the reference parser):

    [name]   <string>                   libhpnn.c:684-691
    [type]   first char L->LNN S->SNN else ANN      libhpnn.c:692-709
    [init]   line containing "generate"/"GENERATE" -> generate,
             else value = kernel filename           libhpnn.c:710-729
    [seed]   unsigned int                           libhpnn.c:730-739
    [input]  unsigned int                           libhpnn.c:740-751
    [hidden] one or more unsigned ints              libhpnn.c:752-775
    [output] unsigned int                           libhpnn.c:776-786
    [train]  B..->BP (BxM->BPM), C->CG, S->SPLX     libhpnn.c:787-805
    [sample_dir] <dir>                              libhpnn.c:806-812
    [test_dir]   <dir>                              libhpnn.c:813-819
"""

from __future__ import annotations

import dataclasses
from typing import IO

from ..utils.nn_log import nn_error, nn_out

NN_TYPE_ANN = "ANN"
NN_TYPE_SNN = "SNN"
NN_TYPE_LNN = "LNN"
NN_TYPE_UKN = "UKN"

NN_TRAIN_BP = "BP"
NN_TRAIN_BPM = "BPM"
NN_TRAIN_CG = "CG"
NN_TRAIN_SPLX = "SPLX"
NN_TRAIN_UKN = "none"


@dataclasses.dataclass
class NNConf:
    name: str | None = None
    type: str = NN_TYPE_UKN
    need_init: bool = False
    seed: int = 0
    f_kernel: str | None = None
    train: str = NN_TRAIN_UKN
    samples: str | None = None
    tests: str | None = None
    # topology, used when need_init (generate) -- [input]/[hidden]/[output]
    n_inputs: int = 0
    hiddens: list[int] = dataclasses.field(default_factory=list)
    n_outputs: int = 0
    # extensions beyond the reference (absent keywords leave defaults):
    batch: int = 0        # [batch] N  -> batched data-parallel training (new)
    dtype: str = "f64"    # [dtype] f64|f32|bf16 -> compute precision (new)
    model: int = 0        # [model] N -> N-way tensor (row) sharding -- the
    #                       reference's MPI/stream strategy (ann.c:913-936),
    #                       reachable from the conf; 0 = -S knob / off
    tile: int = 0         # [tile] N|auto -> batched-tile convergence engine
    #                       (ops.convergence_tile): groups of N samples per
    #                       GEMM-shaped step; -1 = autotuned; 0 = off.  On
    #                       the [batch] route the batch is the group and
    #                       the value sets launch granularity.
    lnn: str = ""         # [lnn] native -> native linear-output LNN kernel
    #                       (hpnn_tpu.train); "" keeps the reference's
    #                       warn-and-SNN-fallthrough byte-for-byte
    trainer: str = ""     # [trainer] cg|bp|bpm -> native trainer registry
    #                       selection (hpnn_tpu.train); cg also coerces
    #                       [train] to CG.  "" = reference dispatch.


def _clean(value: str) -> str:
    """STR_CLEAN: truncate at first space/tab/newline/'#' (common.h:254-262)."""
    out = []
    for ch in value:
        if ch in (" ", "\t", "\n", "#"):
            break
        out.append(ch)
    return "".join(out)


def _after(line: str, key: str) -> str:
    """Text following the keyword, leading blanks skipped (SKIP_BLANK)."""
    rest = line.split(key, 1)[1]
    if rest[:1] == "]":
        rest = rest[1:]
    return rest.lstrip(" \t")


def _get_uint(text: str) -> int | None:
    digits = []
    for ch in text:
        if ch.isdigit():
            digits.append(ch)
        else:
            break
    return int("".join(digits)) if digits else None


def parse_conf(fp: IO[str]) -> NNConf | None:
    conf = NNConf()
    for raw in fp:
        line = raw
        if "[name" in line:
            conf.name = _clean(_after(line, "[name"))
        if "[type" in line:
            first = _after(line, "[type")[:1]
            if first == "L":
                conf.type = NN_TYPE_LNN
            elif first == "S":
                conf.type = NN_TYPE_SNN
            else:
                conf.type = NN_TYPE_ANN
        if "[init" in line:
            if "generate" in line or "GENERATE" in line:
                nn_out("generating kernel!\n")
                conf.need_init = True
            else:
                nn_out("loading kernel!\n")
                conf.need_init = False
                conf.f_kernel = _clean(_after(line, "[init"))
                if not conf.f_kernel:
                    nn_error("Malformed NN configuration file!\n")
                    nn_error("[init] can't read filename\n")
                    return None
        if "[seed" in line:
            v = _get_uint(_after(line, "[seed"))
            if v is None:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[seed] value: {_after(line, '[seed')}")
                return None
            conf.seed = v
        if "[input" in line:
            v = _get_uint(_after(line, "[input"))
            if v is None:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[input] value: {_after(line, '[input')}")
                return None
            conf.n_inputs = v
        if "[hidden" in line:
            rest = _after(line, "[hidden")
            vals: list[int] = []
            for tok in rest.split():
                if tok.isdigit():
                    vals.append(int(tok))
                else:
                    break
            if not vals:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[hidden] value: {rest}")
                return None
            conf.hiddens = vals
        if "[output" in line:
            v = _get_uint(_after(line, "[output"))
            if v is None:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[output] value: {_after(line, '[output')}")
                return None
            conf.n_outputs = v
        if "[train" in line and "[trainer" not in line:
            value = _after(line, "[train")
            first = value[:1]
            if first == "B":
                conf.train = NN_TRAIN_BPM if value[2:3] == "M" else NN_TRAIN_BP
            elif first == "C":
                conf.train = NN_TRAIN_CG
            elif first == "S":
                conf.train = NN_TRAIN_SPLX
            else:
                conf.train = NN_TRAIN_UKN
        if "[sample_dir" in line:
            conf.samples = _clean(_after(line, "[sample_dir"))
        if "[test_dir" in line:
            conf.tests = _clean(_after(line, "[test_dir"))
        # --- extensions (not present in the reference format) ---
        if "[batch" in line:
            v = _get_uint(_after(line, "[batch"))
            if v is None:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[batch] value: {_after(line, '[batch').strip()}\n")
                return None
            conf.batch = v
        if "[dtype" in line:
            conf.dtype = _clean(_after(line, "[dtype")) or "f64"
        if "[model" in line:
            v = _get_uint(_after(line, "[model"))
            if v is None:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[model] value: {_after(line, '[model').strip()}\n")
                return None
            conf.model = v
        if "[trainer" in line:
            value = _clean(_after(line, "[trainer")).lower()
            if value not in ("cg", "bp", "bpm"):
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[trainer] value: {value}\n")
                return None
            conf.trainer = value
            if value == "cg":
                conf.train = NN_TRAIN_CG
            elif value == "bpm":
                conf.train = NN_TRAIN_BPM
            elif value == "bp":
                conf.train = NN_TRAIN_BP
        if "[lnn" in line:
            value = _clean(_after(line, "[lnn")).lower()
            if value != "native":
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"[lnn] value: {value}\n")
                return None
            conf.lnn = value
        if "[tile" in line:
            rest = _after(line, "[tile")
            if _clean(rest).lower() == "auto":
                conf.tile = -1  # autotuned (ops.autotune.decide_tile)
            else:
                v = _get_uint(rest)
                if v is None:
                    nn_error("Malformed NN configuration file!\n")
                    nn_error("[tile] value: "
                             f"{rest.strip()}\n")
                    return None
                conf.tile = v
    if conf.type == NN_TYPE_UKN:
        nn_error("Malformed NN configuration file!\n")
        nn_error("[type] unknown or missing...\n")
        return None
    if conf.need_init:
        for field, label in ((conf.n_inputs, "[input]"), (conf.hiddens, "[hidden]"), (conf.n_outputs, "[output]")):
            if not field:
                nn_error("Malformed NN configuration file!\n")
                nn_error(f"{label} wrong or missing...\n")
                return None
        if any(h == 0 for h in conf.hiddens):
            nn_error("Malformed NN configuration file!\n")
            nn_error("[hidden] some have a 0 neuron content!\n")
    return conf


def load_conf(path: str) -> NNConf | None:
    try:
        fp = open(path, "r")
    except OSError:
        nn_error(f"Error opening configuration file: {path}\n")
        return None
    with fp:
        return parse_conf(fp)


def dump_conf(conf: NNConf, fp: IO[str], kernel=None) -> None:
    """Mirror _NN(dump,conf) (libhpnn.c:885-937)."""
    fp.write("# NN configuration\n")
    fp.write(f"[name] {conf.name}\n")
    fp.write(f"[type] {conf.type if conf.type != NN_TYPE_UKN else NN_TYPE_ANN}\n")
    if conf.need_init:
        fp.write("[init] generate\n")
    elif conf.f_kernel is not None:
        fp.write(f"[init] {conf.f_kernel}\n")
    else:
        fp.write("[init] INVALID <- this should trigger an error\n")
    fp.write(f"[seed] {conf.seed}\n")
    n_inputs = kernel.n_inputs if kernel is not None else conf.n_inputs
    hiddens = kernel.hiddens if kernel is not None else conf.hiddens
    n_outputs = kernel.n_outputs if kernel is not None else conf.n_outputs
    fp.write(f"[inputs] {n_inputs}\n")
    fp.write("[hiddens] " + "".join(f"{h} " for h in hiddens) + "\n")
    fp.write(f"[outputs] {n_outputs}\n")
    fp.write(f"[train] {conf.train}\n")
    if conf.samples is not None:
        fp.write(f"[sample_dir] {conf.samples}\n")
    else:
        fp.write("[sample_dir] INVALID <- this should trigger an error\n")
    if conf.tests is not None:
        fp.write(f"[test_dir] {conf.tests}\n")
    else:
        fp.write("[test_dir] INVALID <- this should trigger an error\n")
