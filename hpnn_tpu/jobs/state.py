"""Persistent job state for the online training service.

One directory per job under the server's ``--job-dir``:

    <job-dir>/
        job-000001/
            job.json        the job record (atomic io.atomic writes)
            nn.conf         the generated training conf (train_nn format)
            corpus/         multipart-uploaded sample files (absent when
                            the submit named a server-side path)
            ckpt/           the job's checkpoint directory (hpnn_tpu/ckpt
                            bundles + manifest -- what hot reload watches
                            and what --resume semantics read)
            kernel.opt      the final trained kernel (same bytes as an
                            offline ``train_nn`` run of the same conf)
            console.log     the captured training console stream

Every ``job.json`` write goes through the shared tmp+fsync+rename
writer (``io/atomic.py``), so a crashed server never leaves a
half-written record, and a restarted server reports its full job
history (jobs that were active at the crash are recovered to
``interrupted`` -- their last epoch-boundary snapshot makes them
resumable with the PR-4 ``--resume`` semantics).

Job lifecycle::

    queued -> running <-> snapshotting -> done
                       \\-> failed | cancelled | interrupted
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

from ..io.atomic import atomic_write_text

JOB_STATES = ("queued", "running", "snapshotting", "done", "failed",
              "cancelled", "interrupted")
# states a crashed server recovers to "interrupted" on restart
ACTIVE_STATES = ("queued", "running", "snapshotting")
TERMINAL_STATES = ("done", "failed", "cancelled", "interrupted")

JOB_JSON = "job.json"
JOB_CONF = "nn.conf"
JOB_CORPUS = "corpus"
JOB_CKPT = "ckpt"
JOB_KERNEL = "kernel.opt"
JOB_CONSOLE = "console.log"


class JobError(Exception):
    """Invalid job submission or an action in a conflicting state."""


@dataclasses.dataclass
class JobState:
    """One training job's record (serialized verbatim to job.json)."""

    job_id: str
    kernel: str                      # target registry kernel name
    params: dict                     # sanitized submit parameters
    path: str                        # the job's directory
    status: str = "queued"
    epochs: int = 1                  # the run's goal
    start_epoch: int = 0             # >0 when resuming a prior job
    epoch: int = 0                   # last epoch the trainer completed
    errors: list = dataclasses.field(default_factory=list)
    generations: list = dataclasses.field(default_factory=list)
    error: str | None = None         # failure diagnostic
    finalized: str | None = None     # "promoted" | "rolled_back" (also
    #                                  "auto_promoted"/"auto_rolled_back"
    #                                  from --auto-promote)
    auto_promote: dict | None = None  # the eval-driven decision record
    baseline_generation: int | None = None  # serving gen at job start
    #                                  (what --auto-promote compares
    #                                  the candidate against)
    resumed_from: str | None = None  # prior job id (resume submits)
    # lease-based auto-resume (ISSUE 14): a running job's lease is
    # refreshed at every epoch boundary (HPNN_JOB_LEASE_S); a job whose
    # record says active but whose lease expired has a dead owner and
    # is recovered to interrupted, and interrupted jobs are re-queued
    # from their newest VERIFIED bundle under a bounded retry budget
    # (HPNN_JOB_MAX_RETRIES, jittered backoff, then failed)
    lease_expires: float = 0.0       # wall clock (persisted timestamp)
    retries: int = 0                 # auto-resume attempts so far
    auto_resume_from: str | None = None  # ckpt dir/bundle to resume from
    # mesh-slice placement (ISSUE 19): {"devices": [ids], "dp", "tp",
    # "size"} once the scheduler grants this job its device slice --
    # carried on /v1/jobs and the job event stream so an operator sees
    # WHERE a job trains; cleared by nothing (the last grant is part of
    # the job's history, like generations)
    slice: dict | None = None
    created: float = 0.0
    started: float = 0.0
    finished: float = 0.0

    @property
    def conf_path(self) -> str:
        return os.path.join(self.path, JOB_CONF)

    @property
    def ckpt_dir(self) -> str:
        # a resumed job continues the PRIOR job's checkpoint history
        # (one run, one manifest -- same contract as train_nn --resume
        # PATH), recorded explicitly so restarts keep the binding
        return self.params.get("ckpt_dir") or os.path.join(self.path,
                                                           JOB_CKPT)

    @property
    def kernel_out(self) -> str:
        return os.path.join(self.path, JOB_KERNEL)

    @property
    def resumable(self) -> bool:
        """An interrupted/cancelled job with at least one snapshot can
        continue via a ``resume_job`` submit (--resume semantics)."""
        return (self.status in ("interrupted", "cancelled")
                and os.path.isfile(os.path.join(self.ckpt_dir,
                                                "manifest.json")))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["resumable"] = self.resumable
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "JobState":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def _record_transition(job_id: str, kernel: str, prev: str,
                       status: str, epoch: int) -> None:
    """One job lifecycle transition as a zero-duration recorder span
    (``?trace=job:<id>`` and the timeline both see it); never raises
    into the store's write path."""
    try:
        from ..obs import trace as obs_trace

        if not obs_trace.enabled():
            return
        import time as _time

        now = _time.monotonic()
        obs_trace.record("job.state", now, now,
                         trace_id=f"job:{job_id}", parent_id=None,
                         job=job_id, kernel=kernel, status=status,
                         previous=prev, epoch=epoch)
    except Exception:
        pass


class JobStore:
    """Directory-backed job index: create/load/update, crash recovery.

    One lock serializes every record mutation AND snapshot read, so HTTP
    threads always see a consistent record while the scheduler thread
    updates it; writes are atomic on disk (io.atomic), so a concurrent
    reader of job.json (ops tooling) sees old-complete or new-complete.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._mu = threading.RLock()
        self._jobs: dict[str, JobState] = {}
        self._next = 1
        self._load_existing()

    # --- persistence ----------------------------------------------------
    def _load_existing(self) -> None:
        for name in sorted(os.listdir(self.root)):
            jpath = os.path.join(self.root, name, JOB_JSON)
            if not os.path.isfile(jpath):
                continue
            try:
                with open(jpath) as fp:
                    job = JobState.from_dict(json.load(fp))
            except (OSError, json.JSONDecodeError, TypeError):
                continue  # a half-created job dir is not fatal
            job.path = os.path.join(self.root, name)  # survive dir moves
            self._jobs[job.job_id] = job
            try:
                self._next = max(self._next,
                                 int(name.split("-")[-1]) + 1)
            except ValueError:
                pass

    def recover(self) -> list[str]:
        """Mark jobs that were active when the previous server died as
        ``interrupted`` (their last snapshot makes them resumable);
        returns the recovered ids."""
        recovered = []
        with self._mu:
            for job in self._jobs.values():
                if job.status in ACTIVE_STATES:
                    job.status = "interrupted"
                    job.error = "server restarted mid-job"
                    self._save_locked(job)
                    recovered.append(job.job_id)
        return recovered

    def _save_locked(self, job: JobState) -> None:
        atomic_write_text(os.path.join(job.path, JOB_JSON),
                          json.dumps(job.to_dict(), indent=1) + "\n")

    # --- API ------------------------------------------------------------
    def create(self, kernel: str, params: dict) -> JobState:
        with self._mu:
            job_id = f"job-{self._next:06d}"
            self._next += 1
            path = os.path.join(self.root, job_id)
            os.makedirs(path, exist_ok=True)
            job = JobState(job_id=job_id, kernel=kernel, params=params,
                           path=path, created=time.time())
            self._jobs[job_id] = job
            self._save_locked(job)
        # the birth transition: the timeline's first jobs entry
        _record_transition(job_id, kernel, "", "queued", 0)
        return job

    def discard(self, job: JobState) -> None:
        """Remove a job that never ran (admission failed mid-submit):
        a rejected submit must leave no record or directory behind."""
        import shutil

        with self._mu:
            self._jobs.pop(job.job_id, None)
            shutil.rmtree(job.path, ignore_errors=True)

    def update(self, job: JobState, **fields) -> None:
        """Mutate + persist under the store lock (the scheduler's only
        write path; HTTP readers snapshot under the same lock).  A
        STATUS change additionally lands in the flight recorder (and
        so the durable span spool) as a zero-duration ``job.state``
        span under the job's trace id -- the incident timeline's jobs
        feed (ISSUE 15); recording happens outside the lock and is a
        no-op while tracing is off."""
        with self._mu:
            prev = job.status
            for k, v in fields.items():
                setattr(job, k, v)
            self._save_locked(job)
            status = job.status
            epoch = job.epoch
        if status != prev:
            _record_transition(job.job_id, job.kernel, prev, status,
                               epoch)

    def get(self, job_id: str) -> JobState | None:
        with self._mu:
            return self._jobs.get(job_id)

    def snapshot(self, job_id: str) -> dict | None:
        with self._mu:
            job = self._jobs.get(job_id)
            return None if job is None else job.to_dict()

    def list(self) -> list[dict]:
        with self._mu:
            return [self._jobs[j].to_dict() for j in sorted(self._jobs)]

    def scan_recovery(self) -> list[JobState]:
        """The records the auto-resume tick cares about (active or
        interrupted), as LIVE objects in id order -- the idle tick
        must not pay a per-job ``asdict`` + ``isfile`` snapshot four
        times a second under the lock the training thread needs."""
        with self._mu:
            return [self._jobs[j] for j in sorted(self._jobs)
                    if self._jobs[j].status in ("running",
                                                "snapshotting",
                                                "interrupted")]

    def trained_epochs(self) -> int:
        """Cumulative epochs trained across all jobs -- in-memory fields
        only (``list()``'s per-job ``to_dict`` stats the ckpt manifest
        on disk; a /metrics scrape must not pay O(jobs) stats under the
        lock the training thread's epoch bookkeeping needs)."""
        with self._mu:
            return sum(max(0, j.epoch - j.start_epoch)
                       for j in self._jobs.values())

    def by_status(self) -> dict[str, int]:
        with self._mu:
            counts: dict[str, int] = {}
            for job in self._jobs.values():
                counts[job.status] = counts.get(job.status, 0) + 1
            return counts
