"""Device-slice placement for concurrent training jobs (ISSUE 19).

One serve process owns one device list (the 8-device CPU mesh in
tests, a TPU pod slice in production).  The job scheduler used to
serialize every training job over the WHOLE list; this module is the
allocator that lets K scheduler workers run K jobs at once, each
pinned to a DISJOINT contiguous slice:

* :class:`SliceManager` owns the device list and a free/busy bitmap.
  ``acquire`` carves a **best-fit contiguous** run (the smallest free
  run that fits, lowest index on ties -- contiguity matters on real
  hardware where slice-local ICI beats hopping the pod, and best-fit
  keeps large runs intact for large asks).
* Grants are **strict FIFO**: a request is granted only when it is the
  oldest pending request.  That is the whole fairness story -- a
  whole-mesh ask parks at the head and DRAINS the mesh (later small
  asks queue behind it instead of starving it forever), and no job can
  leapfrog an older one just because its ask is smaller.
* Slices are reclaimed three ways: the owning worker's ``release`` on
  every terminal path, ``reclaim`` (the scheduler-tick sweep that
  frees any slice whose owner is no longer a live running job --
  defense against a leaked owner, the multi-job analog of a stuck
  queue), and ``close`` (drain).

The slice a job gets determines its training mesh: the worker wraps
``api.train_job(..., devices=slice.devices)`` so every mesh decision
(api.device_slice) sees exactly those devices.  ``dp``/``tp`` on the
placement are bookkeeping for operators (/v1/jobs, /metrics) -- the
authoritative grid is still the job's conf ([batch]/[model]) against
the slice length.
"""

from __future__ import annotations

import threading


def plan_request(params: dict, n_devices: int) -> tuple[int, int]:
    """(slice_size, tp_width) asked for by a job's params.

    ``dp_devices`` * ``tp_devices`` (``model_parallel`` doubles as the
    TP width when ``tp_devices`` is absent -- it is the conf knob that
    actually shards rows).  0 size means "no declaration": the manager
    hands out its fair default share.  Over-asks clamp to the mesh --
    the placement analog of the ``[model]``/``HPNN_*_DEVICES`` clamp
    warnings, except a slice ask is validated at submit time.
    """
    dp = int(params.get("dp_devices") or 0)
    tp = int(params.get("tp_devices") or params.get("model_parallel") or 0)
    if dp <= 0 and tp <= 0:
        return 0, 1
    tp = max(1, tp)
    size = max(1, dp) * tp
    if size > n_devices:
        size = n_devices
    if tp > size:
        tp = size
    return size, tp


class SlicePlacement:
    """One granted slice: the contiguous device run a job is pinned to."""

    __slots__ = ("job_id", "devices", "start", "size", "dp", "tp")

    def __init__(self, job_id: str, devices: list, start: int,
                 size: int, tp: int = 1):
        self.job_id = job_id
        self.devices = list(devices)
        self.start = start
        self.size = size
        self.tp = max(1, min(tp, size))
        self.dp = max(1, size // self.tp)

    def describe(self) -> dict:
        """JSON-safe record carried on the job (/v1/jobs, events)."""
        return {"devices": [getattr(d, "id", i + self.start)
                            for i, d in enumerate(self.devices)],
                "dp": self.dp, "tp": self.tp, "size": self.size}


class SliceManager:
    """Best-fit contiguous slice allocator with strict-FIFO granting."""

    def __init__(self, devices=None, workers: int = 1):
        if devices is None:
            import jax

            devices = jax.devices()
        self.devices = list(devices)
        self.n = len(self.devices)
        self.workers = max(1, int(workers))
        self._free = [True] * self.n
        self._owners: dict[str, SlicePlacement] = {}
        self._pending: list[dict] = []
        self._cv = threading.Condition()
        self._closed = False

    # -- sizing --------------------------------------------------------

    def default_share(self) -> int:
        """Fair share for an undeclared job: the mesh split evenly over
        the worker pool (every worker can hold a default job at once)."""
        return max(1, self.n // self.workers)

    def request_size(self, params: dict) -> tuple[int, int]:
        """(size, tp) for a job's params; size 0 -> the fair share."""
        size, tp = plan_request(params or {}, self.n)
        if size <= 0:
            size = self.default_share()
        return size, tp

    # -- allocation ----------------------------------------------------

    def _best_fit(self, size: int) -> int | None:
        """Start index of the smallest free contiguous run >= size."""
        best = None
        best_len = None
        i = 0
        while i < self.n:
            if not self._free[i]:
                i += 1
                continue
            j = i
            while j < self.n and self._free[j]:
                j += 1
            run = j - i
            if run >= size and (best_len is None or run < best_len):
                best, best_len = i, run
            i = j
        return best

    def try_acquire(self, job_id: str, size: int = 0,
                    tp: int = 1) -> SlicePlacement | None:
        """Non-blocking acquire; still queues behind older waiters
        (returns None rather than leapfrog the FIFO)."""
        with self._cv:
            if self._closed or job_id in self._owners:
                return None
            if self._pending:
                return None
            return self._grant(job_id, size, tp)

    def acquire(self, job_id: str, size: int = 0, tp: int = 1,
                stop: threading.Event | None = None,
                timeout_s: float | None = None) -> SlicePlacement | None:
        """Block until this request is the oldest pending one AND a
        best-fit run frees up; None on stop/close/timeout.  A whole-mesh
        ask therefore drains the mesh: it holds the head of the queue
        until every running slice releases."""
        import time as _time

        deadline = (None if timeout_s is None
                    else _time.monotonic() + timeout_s)
        ticket = {"job_id": job_id}
        with self._cv:
            if self._closed or job_id in self._owners:
                return None
            self._pending.append(ticket)
            try:
                while True:
                    if self._closed:
                        return None
                    if stop is not None and stop.is_set():
                        return None
                    if self._pending[0] is ticket:
                        placed = self._grant(job_id, size, tp)
                        if placed is not None:
                            return placed
                    # grant is tried before the deadline check, so
                    # timeout_s=0.0 means exactly one non-blocking try
                    if deadline is not None \
                            and _time.monotonic() >= deadline:
                        return None
                    self._cv.wait(0.05)
            finally:
                if ticket in self._pending:
                    self._pending.remove(ticket)
                self._cv.notify_all()

    def _grant(self, job_id: str, size: int, tp: int):
        size = max(1, min(int(size) or self.default_share(), self.n))
        start = self._best_fit(size)
        if start is None:
            return None
        for i in range(start, start + size):
            self._free[i] = False
        placed = SlicePlacement(job_id, self.devices[start:start + size],
                                start, size, tp=tp)
        self._owners[job_id] = placed
        return placed

    # -- reclamation ---------------------------------------------------

    def release(self, job_id: str) -> bool:
        with self._cv:
            placed = self._owners.pop(job_id, None)
            if placed is None:
                return False
            for i in range(placed.start, placed.start + placed.size):
                self._free[i] = True
            self._cv.notify_all()
            return True

    def reclaim(self, live) -> list[str]:
        """Free every slice whose owner ``live(job_id)`` disowns.

        The scheduler sweeps this once per tick with "is this job_id
        still installed in my running map" -- a slice whose owner died
        without releasing (worker crash, leaked state) frees within one
        tick instead of deadlocking the queue behind a phantom job.
        """
        with self._cv:
            dead = [j for j in self._owners if not live(j)]
        for j in dead:
            self.release(j)
        return dead

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- visibility ----------------------------------------------------

    def occupancy(self) -> dict:
        """Snapshot for /healthz, /metrics and the bench."""
        with self._cv:
            in_use = sum(1 for f in self._free if not f)
            return {
                "devices_total": self.n,
                "devices_in_use": in_use,
                "slices_active": len(self._owners),
                "queued_placements": len(self._pending),
                "slices": {j: p.describe()
                           for j, p in self._owners.items()},
            }
