"""Bounded FIFO job queue.

Admission control mirrors the serving batcher's philosophy: a full
queue rejects AT SUBMIT TIME (the HTTP layer maps :class:`JobQueueFull`
to 429 + Retry-After) instead of accepting unbounded work the device
can never keep up with.  Training jobs are heavyweight -- the cap is
jobs, not rows -- and one scheduler worker drains the queue strictly in
submit order, so a queued job's position is its ETA story.
"""

from __future__ import annotations

import threading
from collections import deque

from .state import JobState


class JobQueueFull(Exception):
    """Admission rejected: the bounded job queue is at capacity."""


class JobQueue:
    def __init__(self, capacity: int = 8):
        self.capacity = max(1, int(capacity))
        self._q: deque[JobState] = deque()
        self._cv = threading.Condition()
        self._closed = False

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    def submit(self, job: JobState) -> None:
        with self._cv:
            if self._closed:
                raise JobQueueFull("job queue closed (server draining)")
            if len(self._q) >= self.capacity:
                raise JobQueueFull(
                    f"job queue at {len(self._q)}/{self.capacity}")
            self._q.append(job)
            self._cv.notify_all()

    def take(self, timeout_s: float = 0.2) -> JobState | None:
        """Blocking FIFO pop; None on timeout or when closed+empty."""
        with self._cv:
            if not self._q:
                self._cv.wait(timeout=timeout_s)
            if not self._q:
                return None
            return self._q.popleft()

    def requeue_front(self, job: JobState) -> None:
        """Put an already-admitted job back at the head (the scheduler
        took it while paused/draining); never counts against capacity --
        admission already happened."""
        with self._cv:
            self._q.appendleft(job)
            self._cv.notify_all()

    def remove(self, job_id: str) -> bool:
        """Pull a still-queued job out (cancel before it ever runs)."""
        with self._cv:
            for job in self._q:
                if job.job_id == job_id:
                    self._q.remove(job)
                    return True
        return False

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
