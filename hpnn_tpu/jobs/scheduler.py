"""Job scheduler: train-while-serving on pinned mesh slices.

A pool of K worker threads (``--job-workers K``, default 1) drains the
bounded :class:`~.queue.JobQueue` strictly FIFO; each worker acquires a
DISJOINT contiguous device slice from the shared
:class:`~.placement.SliceManager` (best-fit, strict-FIFO grants --
``dp_devices``/``tp_devices``/``model_parallel`` submit params size the
ask, undeclared jobs get the fair default share, a whole-mesh ask
drains the mesh first) and drives its job through the REENTRANT
training entry pinned to that slice (``api.train_job(...,
devices=slice)`` -- the same configure/train_loop/checkpoint path
``train_nn`` runs, so a job's ``kernel.opt`` is byte-identical to the
offline CLI run of the same conf/corpus/seed on a same-sized slice).
The slice is released on EVERY terminal path, and a per-tick
``reclaim`` sweep frees any slice whose owner is no longer installed
(a leaked slice is the multi-job analog of a stuck queue).  Device
sharing with eval traffic stays cooperative and epoch-granular:

* the trainer calls back at EVERY epoch boundary (``on_epoch``); the
  worker updates the persistent job record, flushes the due snapshot,
  hot-reloads the published bundle into the serving registry (the same
  manifest-generation machinery ``--watch-ckpt`` polls, driven
  synchronously here so a swap lands the moment its bundle is durable),
  and then YIELDS: while eval traffic is queued on any batcher, the next
  epoch waits (bounded by ``preempt_wait_s``) -- serve traffic preempts
  training between epochs, never the reverse.  The yield is PER WORKER:
  one job deferring to eval traffic no longer stalls the other workers'
  epochs;
* cancel and graceful drain both latch the job's stop event; the
  in-flight epoch finishes, the checkpoint manager writes a final
  snapshot (the ckpt subsystem's signal machinery, reused verbatim), and
  the job lands ``cancelled`` or ``interrupted`` -- resumable through
  ``resume_job`` submits or an offline ``train_nn --resume``.

The scheduler never touches the device directly: training goes through
the epoch pipeline, eval through the batchers, and the only
coordination between them is the epoch-boundary yield -- which is
exactly the granularity at which the two workloads' jit programs can
interleave without either preempting a launch.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from ..obs import trace as obs_trace
from ..utils import nn_log
from ..utils.nn_log import nn_out, nn_warn
from .placement import SliceManager, plan_request
from .queue import JobQueue, JobQueueFull
from .state import (
    JOB_CONSOLE,
    JOB_CORPUS,
    TERMINAL_STATES,
    JobError,
    JobState,
    JobStore,
)

__all__ = ["JobScheduler", "JobQueueFull", "JobError"]

_TRAINERS = ("BP", "BPM", "CG")
_DTYPES = ("f64", "f32", "bf16")
_TYPES = ("ANN", "SNN", "LNN")

# chunked streaming upload (ISSUE 18 rung 2): a job submitted on its
# FIRST corpus chunk carries this marker in its job dir until the last
# chunk lands -- the runner holds training (bounded by
# HPNN_JOBS_UPLOAD_WAIT_S) while queue admission, conf generation,
# queue dwell and the incremental pack build all overlap the upload
JOB_UPLOAD_MARKER = ".upload-incomplete"

# the eval-preemption gate resumes training only after the batcher
# queues stay drained this many consecutive 1ms ticks -- a saturated
# closed-loop client dips to zero for a tick between a drain and the
# next arrivals, and that must not read as "eval traffic stopped"
YIELD_QUIESCE_TICKS = 10

# console.log prefixes per captured nn_log level (replay-equivalent at
# the verbosity the entries were captured under)
_LOG_PREFIX = {"dbg": "NN(DBG): ", "out": "NN: ", "cout": "",
               "warn": "NN(WARN): ", "error": "NN(ERR): ", "raw": ""}


def _as_int(params: dict, key: str, default: int, floor: int = 0) -> int:
    v = params.get(key, default)
    try:
        v = int(v)
    except (TypeError, ValueError):
        raise JobError(f"'{key}' must be an integer: {v!r}")
    if v < floor:
        raise JobError(f"'{key}' must be >= {floor}: {v}")
    return v


class JobScheduler:
    def __init__(self, app, job_dir: str, capacity: int = 8,
                 preempt_wait_s: float = 2.0,
                 auto_promote: bool = False,
                 auto_resume: bool | None = None,
                 replicate_to: str | None = None,
                 job_workers: int = 1, devices=None):
        from ..utils.env import env_device_cap, env_float, env_int

        self.app = app
        # eval-driven auto-promotion (ISSUE 13 / ROADMAP 2c): after a
        # job lands "done", evaluate the candidate generation against
        # the pre-job baseline on a held-out test dir and promote /
        # roll back automatically (operator endpoints still override)
        self.auto_promote = bool(auto_promote)
        # lease-based auto-resume (ISSUE 14): interrupted jobs (crash
        # recovery, expired leases) are re-queued from their newest
        # VERIFIED local-or-replicated bundle, bounded by a retry
        # budget with jittered backoff, then failed with a reason
        if auto_resume is None:
            auto_resume = os.environ.get("HPNN_JOB_AUTO_RESUME") == "1"
        self.auto_resume = bool(auto_resume)
        # off-host bundle replication destination (--replicate-to):
        # each job's CheckpointManager ships verified bundles there,
        # and auto-resume restores from it when the local dir is gone
        self.replicate_to = replicate_to \
            or os.environ.get("HPNN_REPLICATE_TO") or None
        self.lease_s = env_float("HPNN_JOB_LEASE_S", 60.0, lo=1.0)
        self.max_retries = env_int("HPNN_JOB_MAX_RETRIES", 3, lo=0)
        self.retry_backoff_s = env_float("HPNN_JOB_RETRY_BACKOFF_S",
                                         1.0, lo=0.0)
        self.auto_resumes_total = 0
        self.store = JobStore(job_dir)
        recovered = self.store.recover()
        if recovered:
            nn_out(f"jobs: recovered {len(recovered)} interrupted "
                   f"job(s) from {job_dir}: {', '.join(recovered)}\n")
        self.queue = JobQueue(capacity)
        self.preempt_wait_s = float(preempt_wait_s)
        # auto-resume schedule: job_id -> monotonic due time (jittered
        # exponential in the job's persisted retry count)
        self._resume_due: dict[str, float] = {}
        self._resume_last_scan = 0.0
        self._mu = threading.Lock()
        # in-flight chunked uploads: job_id -> {"writer", "chunks",
        # "deadline"} (guarded by _mu; sessions die with the process --
        # the on-disk marker alone decides whether a job may train)
        self._uploads: dict[str, dict] = {}
        self.upload_chunks_total = 0
        self.upload_wait_s = env_float("HPNN_JOBS_UPLOAD_WAIT_S",
                                       120.0, lo=1.0)
        # mesh-slice placement (ISSUE 19): each worker pins its job to a
        # disjoint contiguous device slice.  The HPNN_DP_DEVICES env
        # knob keeps its pre-placement meaning as the DEFAULT-slice
        # bound: an undeclared job's fair share is additionally capped
        # by it (a declared dp_devices/tp_devices ask is explicit and
        # wins, exactly like an explicit devices= list wins in api)
        self.workers = max(1, int(job_workers))
        self.slices = SliceManager(devices=devices, workers=self.workers)
        self._default_cap = env_device_cap("HPNN_DP_DEVICES",
                                           self.slices.n)
        # per-job running state: job_id -> {"job", "stop", "cancel",
        # "slice"} (guarded by _mu).  _pending_cancel keeps latching
        # cancels that land in the pop-to-install window.
        self._running: dict[str, dict] = {}
        self._pending_cancel: set[str] = set()
        self._draining = False
        self._paused = False
        self._closed = False
        self._threads = [
            threading.Thread(target=self._loop, args=(i,),
                             name=f"hpnn-job-worker-{i}", daemon=True)
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # --- submission ------------------------------------------------------
    def submit(self, kernel: str, params: dict,
               corpus_files: list[tuple[str, bytes]] | None = None,
               upload_incomplete: bool = False) -> JobState:
        """Validate, materialize the job dir (conf + uploaded corpus) and
        enqueue.  Raises :class:`JobError` (HTTP 400) on bad parameters,
        :class:`JobQueueFull` (429) when the queue is at capacity.

        ``upload_incomplete`` (chunked uploads): the job enters the
        queue with only its first corpus chunk on disk and a marker
        that holds the runner until :meth:`upload_chunk` sees the last
        chunk -- the marker is written BEFORE the queue submit so an
        instantly-scheduled job can never train on a partial corpus."""
        model = self.app.registry.get(kernel)
        if model is None:
            raise JobError(f"unknown kernel '{kernel}'")
        if not isinstance(params, dict):
            raise JobError("params must be a JSON object")
        if self.queue.depth() >= self.queue.capacity:
            # reject BEFORE creating the job dir: a 429 must leave no
            # half-registered job behind
            raise JobQueueFull(
                f"job queue at {self.queue.depth()}/{self.queue.capacity}")
        clean = self._sanitize(model, params, corpus_files)
        job = self.store.create(kernel, clean)
        try:
            if corpus_files:
                cdir = os.path.join(job.path, JOB_CORPUS)
                os.makedirs(cdir, exist_ok=True)
                for name, data in corpus_files:
                    base = os.path.basename(name)
                    if not base or base.startswith("."):
                        raise JobError(f"bad corpus file name {name!r}")
                    with open(os.path.join(cdir, base), "wb") as fp:
                        fp.write(data)
                clean["samples"] = cdir
            if upload_incomplete:
                with open(os.path.join(job.path, JOB_UPLOAD_MARKER),
                          "w") as fp:
                    fp.write(f"{int(time.time())}\n")
            job.epochs = clean["epochs"]
            job.start_epoch = clean.get("start_epoch", 0)
            job.epoch = job.start_epoch
            job.resumed_from = clean.get("resumed_from")
            self._write_conf(job, model, clean)
            self.store.update(job)
            self.queue.submit(job)
        except Exception:
            # the job never ran -- a failed admission (429 racing the
            # pre-check, bad upload name, closed queue) must leave no
            # phantom record or directory behind
            self.store.discard(job)
            raise
        nn_out(f"jobs: {job.job_id} queued for kernel '{kernel}' "
               f"({clean['epochs']} epoch(s), train={clean['train']})\n")
        return job

    # --- chunked streaming upload (ISSUE 18 rung 2) -----------------------
    def submit_chunked(self, kernel: str, params: dict,
                       first_chunk: list[tuple[str, bytes]]) -> JobState:
        """Admit a job on its FIRST corpus chunk: the job is queued
        immediately (conf written, marker held), the chunk's rows enter
        an incremental pack build, and later :meth:`upload_chunk` calls
        append the rest -- training starts the moment the final chunk
        lands (or the runner reaches the job, whichever is later)."""
        if not first_chunk:
            raise JobError("chunk 1 must carry at least one corpus file")
        model = self.app.registry.get(kernel)
        if model is None:
            raise JobError(f"unknown kernel '{kernel}'")
        job = self.submit(kernel, params, corpus_files=first_chunk,
                          upload_incomplete=True)
        from ..io.corpus import ChunkedPackWriter

        writer = ChunkedPackWriter(os.path.join(job.path, JOB_CORPUS),
                                   model.n_inputs, model.n_outputs)
        writer.add_sample_files(
            [os.path.basename(n) for n, _ in first_chunk])
        with self._mu:
            self._uploads[job.job_id] = {
                "writer": writer, "chunks": 1,
                "deadline": time.monotonic() + self.upload_wait_s}
            self.upload_chunks_total += 1
        return job

    def upload_chunk(self, job_id: str,
                     corpus_files: list[tuple[str, bytes]],
                     final: bool) -> dict:
        """Append one corpus chunk to a job admitted by
        :meth:`submit_chunked`.  The final chunk (which may be empty --
        a bare close) finalizes the incremental pack and releases the
        runner's upload hold."""
        with self._mu:
            sess = self._uploads.get(job_id)
        if sess is None:
            job = self.store.get(job_id)
            if job is None:
                raise JobError(f"unknown job '{job_id}'")
            raise JobError(f"job '{job_id}' has no open chunked upload")
        job = self.store.get(job_id)
        if job is None or job.status in TERMINAL_STATES:
            self._drop_upload(job_id, aborted=True)
            raise JobError(f"job '{job_id}' is no longer accepting "
                           "corpus chunks")
        cdir = os.path.join(job.path, JOB_CORPUS)
        names = []
        for name, data in corpus_files:
            base = os.path.basename(name)
            if not base or base.startswith("."):
                raise JobError(f"bad corpus file name {name!r}")
            path = os.path.join(cdir, base)
            if os.path.exists(path):
                raise JobError(f"duplicate corpus file {base!r}")
            with open(path, "wb") as fp:
                fp.write(data)
            names.append(base)
        if names:
            sess["writer"].add_sample_files(names)
        with self._mu:
            sess["chunks"] += 1
            self.upload_chunks_total += 1
            chunks = sess["chunks"]
        if final:
            # assemble the warm pack BEFORE releasing the hold: the
            # runner's cold load then replays the pack instead of
            # re-reading every uploaded file (best-effort -- a refused
            # pack still trains from the source files)
            sess["writer"].finalize()
            self._drop_upload(job_id, aborted=False)
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(job.path, JOB_UPLOAD_MARKER))
        return {"job": job_id, "chunks": chunks,
                "complete": bool(final)}

    def _drop_upload(self, job_id: str, aborted: bool) -> None:
        with self._mu:
            sess = self._uploads.pop(job_id, None)
        if sess is not None and aborted:
            sess["writer"].abort()

    def _await_upload(self, job: JobState,
                      stop: threading.Event) -> bool:
        """Hold the runner until the job's corpus upload completes (the
        on-disk marker disappears).  Returns False -- with the job's
        terminal status already recorded -- when the hold ends in
        cancellation or times out."""
        marker = os.path.join(job.path, JOB_UPLOAD_MARKER)
        if not os.path.exists(marker):
            return True
        with self._mu:
            sess = self._uploads.get(job.job_id)
        deadline = (sess["deadline"] if sess is not None
                    else time.monotonic() + self.upload_wait_s)
        self.store.update(job, status="running", started=time.time(),
                          lease_expires=(time.time()
                                         + self.upload_wait_s
                                         + self.lease_s))
        while os.path.exists(marker):
            if stop.is_set():
                self._drop_upload(job.job_id, aborted=True)
                status = ("cancelled" if self._is_cancelled(job.job_id)
                          else "interrupted")
                self.store.update(job, status=status,
                                  error="stopped during corpus upload",
                                  finished=time.time(),
                                  lease_expires=0.0)
                nn_out(f"jobs: {job.job_id} {status} during corpus "
                       "upload\n")
                return False
            if time.monotonic() > deadline:
                self._drop_upload(job.job_id, aborted=True)
                self.store.update(
                    job, status="failed",
                    error=f"corpus upload incomplete after "
                          f"{self.upload_wait_s:.0f}s",
                    finished=time.time(), lease_expires=0.0)
                nn_out(f"jobs: {job.job_id} failed: corpus upload "
                       f"incomplete after {self.upload_wait_s:.0f}s\n")
                return False
            time.sleep(0.05)
        return True

    def _sanitize(self, model, params: dict,
                  corpus_files) -> dict:
        clean: dict = {}
        clean["epochs"] = _as_int(params, "epochs", 1, floor=1)
        clean["ckpt_every"] = _as_int(params, "ckpt_every", 1)
        clean["ckpt_keep"] = _as_int(params, "ckpt_keep", 0)
        clean["seed"] = _as_int(params, "seed", 1)
        train = str(params.get("train") or model.nn.conf.train
                    or "BP").upper()
        if train not in _TRAINERS:
            raise JobError(f"'train' must be one of {_TRAINERS}: {train}")
        clean["train"] = train
        ktype = str(params.get("type") or model.kind).upper()
        if ktype not in _TYPES:
            raise JobError(f"'type' must be one of {_TYPES}: {ktype}")
        clean["type"] = ktype
        # the native linear-head gate rides the job conf: inherited
        # from the served model unless overridden at submit, so a job
        # against a native-LNN kernel trains the same head it serves
        lnn = str(params.get("lnn")
                  or getattr(model.nn.conf, "lnn", None) or "").lower()
        if lnn and lnn != "native":
            raise JobError(f"'lnn' must be 'native': {lnn}")
        clean["lnn"] = lnn
        dtype = str(params.get("dtype") or model.dtype_name)
        if dtype not in _DTYPES:
            raise JobError(f"'dtype' must be one of {_DTYPES}: {dtype}")
        clean["dtype"] = dtype
        # mesh-slice placement ask (ISSUE 19): dp_devices x tp_devices
        # sizes the slice (model_parallel doubles as the TP width and
        # emits the conf's [model] row-sharding line; batch emits
        # [batch] so the DP route engages over the slice).  Undeclared
        # jobs take the fair default share at grant time; an over-ask
        # clamps to the mesh exactly like the [model] clamp.
        for key in ("dp_devices", "tp_devices", "model_parallel",
                    "batch"):
            v = _as_int(params, key, 0)
            if v:
                clean[key] = v
        hidden = params.get("hidden", list(model.topology[1:-1]))
        if isinstance(hidden, int):
            hidden = [hidden]
        try:
            hidden = [int(h) for h in hidden]
        except (TypeError, ValueError):
            raise JobError(f"'hidden' must be int(s): {hidden!r}")
        if not hidden or any(h < 1 for h in hidden):
            raise JobError(f"'hidden' layers must be >= 1: {hidden}")
        clean["hidden"] = hidden
        tests = params.get("test_samples")
        if tests:
            # held-out eval corpus for --auto-promote: server-side dir,
            # validated at submit like 'samples'
            tests = os.path.abspath(str(tests))
            if not os.path.isdir(tests):
                raise JobError(
                    f"'test_samples' is not a directory: {tests}")
            clean["test_samples"] = tests
        resume_id = params.get("resume_job")
        if resume_id:
            prev = self.store.get(str(resume_id))
            if prev is None:
                raise JobError(f"unknown resume_job '{resume_id}'")
            if not prev.resumable:
                raise JobError(
                    f"job '{resume_id}' is not resumable "
                    f"(status {prev.status})")
            clean["resumed_from"] = prev.job_id
            # continue the prior job's checkpoint history (one run, one
            # manifest -- train_nn --resume PATH semantics) and, by
            # default, its corpus and goal
            clean["ckpt_dir"] = prev.ckpt_dir
            clean["start_epoch"] = prev.epoch
            clean.setdefault("samples", prev.params.get("samples"))
            if "epochs" not in params:
                clean["epochs"] = max(prev.epochs, prev.epoch)
            # a resumed job re-acquires an EQUAL-SIZE slice (not
            # necessarily the same devices -- the trajectory depends
            # only on the mesh shape, so resume stays byte-exact)
            for key in ("dp_devices", "tp_devices", "model_parallel",
                        "batch"):
                if key not in clean and prev.params.get(key):
                    clean[key] = int(prev.params[key])
        if corpus_files:
            if params.get("samples"):
                raise JobError(
                    "pass a server-side 'samples' path OR upload corpus "
                    "files, not both")
        else:
            # an explicit submit-time path overrides the resumed job's
            # inherited corpus
            samples = params.get("samples") or clean.get("samples")
            if not samples:
                raise JobError("missing 'samples' (server-side corpus "
                               "path) or a multipart corpus upload")
            samples = os.path.abspath(str(samples))
            if not os.path.isdir(samples):
                raise JobError(f"'samples' is not a directory: {samples}")
            clean["samples"] = samples
        return clean

    def _write_conf(self, job: JobState, model, clean: dict) -> None:
        """The generated train_nn conf -- the SAME grammar the offline
        CLI parses, so the parity contract is literal: train_nn on this
        file reproduces the job byte-for-byte."""
        lines = [
            f"[name] {job.kernel}",
            f"[type] {clean['type']}",
            "[init] generate",
            f"[seed] {clean['seed']}",
            f"[input] {model.n_inputs}",
            "[hidden] " + " ".join(str(h) for h in clean["hidden"]),
            f"[output] {model.n_outputs}",
            f"[train] {clean['train']}",
            f"[dtype] {clean['dtype']}",
            f"[sample_dir] {clean['samples']}",
        ]
        # slice-route keywords: the SAME [batch]/[model] grammar the
        # offline CLI parses, so a pinned job's parity run is literally
        # "train_nn on this conf under an equal-sized device view"
        if clean.get("batch"):
            lines.append(f"[batch] {clean['batch']}")
        if clean.get("model_parallel"):
            lines.append(f"[model] {clean['model_parallel']}")
        if clean["train"] == "CG":
            # [train] CG alone would warn-and-fall-through like the
            # reference; the keyword engages the native batched trainer
            lines.insert(lines.index(f"[train] {clean['train']}") + 1,
                         "[trainer] cg")
        if clean.get("lnn"):
            lines.insert(lines.index(f"[type] {clean['type']}") + 1,
                         f"[lnn] {clean['lnn']}")
        with open(job.conf_path, "w") as fp:
            fp.write("\n".join(lines) + "\n")

    # --- workers ----------------------------------------------------------
    def _is_cancelled(self, job_id: str) -> bool:
        with self._mu:
            run = self._running.get(job_id)
            return bool(run is not None and run["cancel"])

    def _reclaim_tick(self) -> None:
        """Free any slice whose owner is no longer an installed running
        job -- one tick after a worker dies without its finally (or any
        other leak), the next queued job can place.  Normal releases
        happen inline in the worker; this sweep is the backstop that
        keeps a slice leak from becoming the new deadlock."""
        def live(job_id: str) -> bool:
            with self._mu:
                return job_id in self._running
        for job_id in self.slices.reclaim(live):
            nn_warn(f"jobs: reclaimed leaked device slice of "
                    f"{job_id}\n")
            nn_log.nn_event("job_slice_reclaimed", job=job_id)

    def _loop(self, widx: int = 0) -> None:
        while not self._closed:
            if widx == 0:
                # housekeeping rides worker 0's poll cadence: one tick
                # is the reclaim/auto-resume latency bound
                try:
                    self._reclaim_tick()
                    if self.auto_resume:
                        self._auto_resume_tick()
                except Exception as exc:  # noqa: BLE001 -- the tick is
                    # recovery machinery; it must never kill the worker
                    nn_warn(f"jobs: housekeeping tick error (loop "
                            f"continues): {type(exc).__name__}: "
                            f"{exc}\n")
            job = self.queue.take(timeout_s=0.1)
            if job is None:
                continue
            if self._paused:
                # pause() may land while this thread is parked in
                # take(): hand the job back untouched instead of
                # running it behind the pause
                self.queue.requeue_front(job)
                time.sleep(0.02)
                continue
            with self._mu:
                if self._closed or self._draining:
                    # the server is going down: the queued job never ran,
                    # leave it resumable instead of silently dropping it
                    self._pending_cancel.discard(job.job_id)
                    self.store.update(job, status="interrupted",
                                      error="server shutdown before run",
                                      finished=time.time())
                    continue
                run = {"job": job, "stop": threading.Event(),
                       "cancel": False, "slice": None}
                self._running[job.job_id] = run
                if job.job_id in self._pending_cancel:
                    # a cancel latched while the job was between the
                    # queue and this install: honor it now
                    self._pending_cancel.discard(job.job_id)
                    run["cancel"] = True
                    run["stop"].set()
            try:
                self._place_and_run(job, run)
            except Exception as exc:  # noqa: BLE001 -- job isolation:
                # one broken job must not kill the scheduler
                nn_warn(f"jobs: {job.job_id} failed: {exc}\n")
                self.store.update(job, status="failed",
                                  error=f"{type(exc).__name__}: {exc}",
                                  finished=time.time())
            finally:
                self.slices.release(job.job_id)
                with self._mu:
                    self._running.pop(job.job_id, None)
                    # a cancel that raced job completion leaves a stale
                    # latch -- the job is terminal, drop it
                    self._pending_cancel.discard(job.job_id)

    def _place_and_run(self, job: JobState, run: dict) -> None:
        """Acquire the job's device slice (blocking, FIFO -- the job
        stays ``queued`` while it waits), persist the placement, run."""
        size, tp = plan_request(job.params, self.slices.n)
        if size <= 0:
            # undeclared ask: fair share of the mesh over the worker
            # pool, bounded by the HPNN_DP_DEVICES default-slice cap
            size = min(self.slices.default_share(), self._default_cap)
        placed = None
        if not run["stop"].is_set():
            placed = self.slices.acquire(job.job_id, size, tp=tp,
                                         stop=run["stop"])
        if placed is None:
            # stopped (cancel/drain) while waiting for a slice, or the
            # manager closed under us: the job never trained
            status = ("cancelled" if run["cancel"] else "interrupted")
            self.store.update(job, status=status,
                              error="stopped before slice grant",
                              finished=time.time(), lease_expires=0.0)
            nn_out(f"jobs: {job.job_id} {status} before slice grant\n")
            return
        run["slice"] = placed
        self.store.update(job, slice=placed.describe())
        nn_log.nn_event("job_slice_granted", job=job.job_id,
                        **placed.describe())
        self._run_job(job, run["stop"], placed.devices)

    # --- lease-based auto-resume (ISSUE 14) -------------------------------
    def _auto_resume_tick(self) -> None:
        """One recovery scan (throttled; runs on the worker thread
        between queue polls): expired-lease actives are recovered to
        ``interrupted``, interrupted jobs are scheduled for re-queue
        under the retry budget, and due schedules fire."""
        now = time.monotonic()
        if now - self._resume_last_scan < 0.25:
            return
        self._resume_last_scan = now
        if self._draining or self._closed or self._paused:
            return
        lease_now = time.time()  # leases are persisted wall-clock
        with self._mu:
            running = set(self._running)
        candidates = self.store.scan_recovery()
        if not candidates:
            self._resume_due.clear()  # nothing interrupted remains
            return
        for job in candidates:
            job_id = job.job_id
            if job_id in running:
                continue
            if (job.status in ("running", "snapshotting")
                    and job.lease_expires
                    and lease_now > job.lease_expires):
                # an active record nobody is driving: the owner died
                # without even the restart-recovery path running (e.g.
                # a shared job dir whose other host is gone)
                nn_warn(f"jobs: {job_id} lease expired "
                        f"{lease_now - job.lease_expires:.1f}s ago; "
                        "recovering to interrupted\n")
                self.store.update(job, status="interrupted",
                                  error="lease expired")
                nn_log.nn_event("job_lease_expired", job=job_id,
                                kernel=job.kernel)
            if job.status != "interrupted":
                self._resume_due.pop(job_id, None)
                continue
            if job.job_id in self._resume_due:
                if now >= self._resume_due[job_id]:
                    self._resume_due.pop(job_id, None)
                    self._try_auto_resume(job)
                continue
            if job.retries >= self.max_retries:
                self.store.update(
                    job, status="failed",
                    error=f"auto-resume retry budget exhausted "
                          f"({job.retries}/{self.max_retries})",
                    finished=time.time())
                nn_log.nn_event("job_auto_resume_failed", job=job_id,
                                kernel=job.kernel, retries=job.retries)
                nn_warn(f"jobs: {job_id} failed: auto-resume retry "
                        f"budget exhausted "
                        f"({job.retries}/{self.max_retries})\n")
                continue
            import random

            delay = (self.retry_backoff_s * (2.0 ** job.retries)
                     * (0.5 + random.random()))
            self._resume_due[job_id] = now + delay

    def _newest_intact_bundle(self, ckpt_dir: str):
        """(bundle path, epoch) of the newest VERIFIED bundle, without
        materializing the weight arrays -- the actual state load
        happens once, inside train_job's resume path."""
        import json as _json

        from .. import ckpt

        for bundle in ckpt.candidate_bundles(ckpt_dir):
            ok, reason = ckpt.verify_bundle(bundle)
            if not ok:
                nn_log.nn_event("ckpt_fallback", bundle=bundle,
                                reason=reason)
                continue
            try:
                with open(os.path.join(bundle,
                                       "snapshot.json")) as fp:
                    meta = _json.load(fp)
                return bundle, int(meta.get("epoch", 0))
            except (OSError, ValueError, UnicodeDecodeError):
                continue
        return None, 0

    def _try_auto_resume(self, job: JobState) -> None:
        """Re-queue one interrupted job from its newest VERIFIED
        bundle: the local checkpoint dir's last-good-fallback walk
        first, the replica destination when nothing local is intact.
        A job with no intact bundle anywhere restarts from scratch --
        the trajectory is deterministic, so the final kernel is
        byte-identical either way."""
        ckpt_dir = job.ckpt_dir
        bundle, epoch = (None, 0)
        if os.path.isdir(ckpt_dir):
            bundle, epoch = self._newest_intact_bundle(ckpt_dir)
        if bundle is None and self.replicate_to:
            from ..ckpt.replicate import restore_bundle, resolve_scope

            with nn_log.capture():  # restore warnings belong to the
                # event stream, not the serve console
                restored = restore_bundle(
                    self.replicate_to, resolve_scope(ckpt_dir),
                    ckpt_dir, auth_token=self.app.auth_token)
            if restored is not None:
                bundle, epoch = self._newest_intact_bundle(ckpt_dir)
        resume_from = ckpt_dir if bundle is not None else None
        self.store.update(job, status="queued", retries=job.retries + 1,
                          epoch=epoch, auto_resume_from=resume_from,
                          error=None, lease_expires=0.0)
        try:
            self.queue.submit(job)
        except JobQueueFull:
            # the queue is busy: back off and try again on a later
            # scan WITHOUT burning retry budget (nothing was attempted)
            self.store.update(job, status="interrupted",
                              retries=job.retries - 1,
                              error="auto-resume deferred (queue full)")
            return
        self.auto_resumes_total += 1
        nn_log.nn_event("job_auto_resume", job=job.job_id,
                        kernel=job.kernel, retry=job.retries,
                        from_epoch=epoch,
                        verified_bundle=os.path.basename(bundle)
                        if bundle else None)
        nn_out(f"jobs: {job.job_id} auto-resumed (attempt "
               f"{job.retries}/{self.max_retries}) from "
               f"{'epoch %d' % epoch if bundle else 'scratch'}\n")

    def _run_job(self, job: JobState, stop: threading.Event,
                 devices=None) -> None:
        # one trace per job, keyed by the job id itself: every epoch
        # span, snapshot write and hot swap on this (worker) thread
        # nests under it -- `GET /v1/debug/trace?trace=job:<id>` is the
        # job's whole execution tree (ISSUE 8)
        with obs_trace.span("jobs.run", trace_id=f"job:{job.job_id}",
                            job=job.job_id, kernel=job.kernel,
                            epochs=job.epochs):
            self._run_job_traced(job, stop, devices)

    def _run_job_traced(self, job: JobState, stop: threading.Event,
                        devices=None) -> None:
        from ..api import train_job

        # chunked upload in flight: hold training until the last chunk
        # lands (the queue dwell already overlapped the upload; any
        # remaining wait is bounded by HPNN_JOBS_UPLOAD_WAIT_S)
        if not self._await_upload(job, stop):
            return
        model = self.app.registry.get(job.kernel)
        if self.auto_promote and model is not None:
            # pin the pre-job serving generation NOW: per-epoch swaps
            # bump + prune generations, and "promote if better" means
            # better than what was serving BEFORE this job.  Touch the
            # device weights first: retention snapshots the holder, and
            # on a server that has taken no traffic yet the holder does
            # not exist -- the swap would then rebuild containers and
            # retain NOTHING, silently losing the baseline
            model.weights()
            self.store.update(job,
                              baseline_generation=model.generation)
        self.store.update(job, status="running", started=time.time(),
                          lease_expires=time.time() + self.lease_s)
        ckpt_dir = job.ckpt_dir
        watch_state = {"gen": 0}
        resume = job.auto_resume_from \
            or ((job.resumed_from and ckpt_dir) or None)

        def on_epoch(epoch: int, manager) -> None:
            due = (manager is not None and manager.every
                   and epoch % manager.every == 0) or epoch >= job.epochs
            errors = list(manager.errors) if manager is not None else []
            # the epoch boundary IS the lease heartbeat: a record whose
            # lease lapses this far means the driving process died
            lease = time.time() + self.lease_s
            if due and manager is not None:
                # snapshotting: the async bundle write must be durable
                # before the registry swaps it in
                self.store.update(job, status="snapshotting",
                                  epoch=epoch, errors=errors,
                                  lease_expires=lease)
                manager.flush()
                self._reload_into_serving(job, ckpt_dir, watch_state)
                self.store.update(job, status="running")
            else:
                self.store.update(job, epoch=epoch, errors=errors,
                                  lease_expires=lease)
            self._yield_to_eval(stop)

        entries: list = []
        with nn_log.capture(entries):
            result = train_job(
                job.conf_path, epochs=job.epochs, ckpt_dir=ckpt_dir,
                ckpt_every=job.params.get("ckpt_every", 1),
                ckpt_keep=job.params.get("ckpt_keep", 0),
                kernel_out=job.kernel_out, resume=resume,
                stop=stop, on_epoch=on_epoch,
                replicate_to=self.replicate_to,
                auth_token=self.app.auth_token, devices=devices)
        self._write_console(job, entries)
        # record_final bumped the manifest generation: swap the finished
        # kernel in (same weights as the last bundle, but the bump keeps
        # any external --watch-ckpt watcher coherent with us)
        self._reload_into_serving(job, ckpt_dir, watch_state)
        if not result["ok"]:
            status, error = "failed", result["error"]
        elif result["interrupted"]:
            status = ("cancelled" if self._is_cancelled(job.job_id)
                      else "interrupted")
            error = None
        else:
            status, error = "done", None
        self.store.update(job, status=status, error=error,
                          epoch=result["epoch"],
                          errors=list(result["errors"]),
                          finished=time.time(), lease_expires=0.0)
        nn_out(f"jobs: {job.job_id} {status} at epoch "
               f"{result['epoch']}/{job.epochs}\n")
        if status == "done" and self.auto_promote:
            try:
                self._auto_promote(job)
            except Exception as exc:  # noqa: BLE001 -- the decision is
                # an optimization on a DONE job: a broken eval must not
                # re-fail it (the operator endpoints still work)
                nn_warn(f"jobs: {job.job_id} auto-promote failed: "
                        f"{type(exc).__name__}: {exc}\n")
                self.store.update(job, auto_promote={
                    "action": "skipped",
                    "reason": f"{type(exc).__name__}: {exc}"})

    # --- eval-driven auto-promotion (ISSUE 13 / ROADMAP 2c) ---------------
    def _skip_promote(self, job: JobState, reason: str) -> None:
        nn_out(f"jobs: {job.job_id} auto-promote skipped: {reason}\n")
        self.store.update(job, auto_promote={"action": "skipped",
                                             "reason": reason})

    def _eval_generation(self, kernel: str, xs, ts, gen: int,
                         objective: str = "accuracy"):
        """Test error of one pinned generation over the test rows,
        THROUGH the serving path (batcher pinned submits): the eval
        traffic is real traffic -- it rides the same A/B generation
        counters a canary fraction rides, which is exactly the
        evidence the decision records.  ``objective`` picks the error
        metric: 'accuracy' (argmax classification error fraction, the
        ANN/SNN default) or 'mse' (mean squared error, the regression
        objective auto-promote uses for linear-head LNN kernels).
        Returns (error, generation that actually served, requests)."""
        import numpy as np

        b = self.app.batchers.get(kernel)
        if b is None:
            raise JobError(f"kernel '{kernel}' has no batcher")
        wrong = requests = 0
        sq_sum = 0.0
        served_all: set[int] = set()
        for i in range(0, xs.shape[0], b.max_batch):
            chunk = np.asarray(xs[i:i + b.max_batch], dtype=np.float64)
            outs, served = b.submit(chunk, 30.0, gen=gen,
                                    return_gen=True)
            served = int(served if served is not None else gen)
            served_all.add(served)
            self.app.metrics.count_generation(kernel, served)
            if objective == "mse":
                d = (np.asarray(outs, np.float64)
                     - np.asarray(ts[i:i + chunk.shape[0]], np.float64))
                sq_sum += float(np.sum(d * d))
            else:
                want = np.argmax(ts[i:i + chunk.shape[0]], axis=1)
                wrong += int(np.sum(np.argmax(outs, axis=1) != want))
            requests += 1
        if objective == "mse":
            err = sq_sum / float(xs.shape[0] * ts.shape[1])
        else:
            err = wrong / float(xs.shape[0])
        return err, served_all, requests

    def _auto_promote(self, job: JobState) -> None:
        """Promote-if-better: evaluate the finished job's candidate
        generation against the pre-job baseline on a held-out test dir
        (the job's ``test_samples`` param, falling back to the conf's
        ``[test_dir]``) and finalize -- promote on no-regression, roll
        back on regression.  The decision record (errors, generations,
        the A/B canary counters as served-traffic evidence) lands in
        the job's persistent state and a structured ``auto_promote``
        event."""
        from ..api import list_sample_dir
        from ..io import corpus as corpus_io

        model = self.app.registry.get(job.kernel)
        if model is None:
            return self._skip_promote(job, "kernel no longer registered")
        if not job.generations:
            return self._skip_promote(job, "job landed no generation")
        table = model.generation_table()
        candidate = table["current"]
        ab = table["ab_window"]
        job_gens = set(int(g) for g in job.generations)
        # baseline preference: the generation serving at job START
        # (pinned above) while still retained; else the A/B window's
        # prev; else the newest retained non-job generation.  A job
        # whose per-epoch swaps pruned every pre-job generation
        # (ckpt_every=1, small gen_keep) falls through to skip --
        # submit with ckpt_every=0 (final-swap-only) for a clean
        # before/after comparison
        baseline = None
        if (job.baseline_generation is not None
                and job.baseline_generation in table["retained"]):
            baseline = int(job.baseline_generation)
        elif ab and ab.get("prev") is not None:
            baseline = int(ab["prev"])
        else:
            prior = [g for g in table["retained"] if g not in job_gens]
            if prior:
                baseline = max(prior)
        if baseline is None:
            return self._skip_promote(
                job, "no retained pre-job baseline generation "
                "(submit with ckpt_every=0, or raise gen_keep)")
        test_dir = job.params.get("test_samples") or model.nn.conf.tests
        if not test_dir or not os.path.isdir(str(test_dir)):
            return self._skip_promote(
                job, "no test dir (pass 'test_samples' in the submit "
                "or a [test_dir] in the serving conf)")
        test_dir = str(test_dir)
        names = list_sample_dir(test_dir)
        if not names:
            return self._skip_promote(job,
                                      f"test dir {test_dir} is empty")
        with obs_trace.span("jobs.auto_promote", job=job.job_id,
                            kernel=job.kernel, candidate=candidate,
                            baseline=baseline):
            _events, xs, ts = corpus_io.load_ordered(
                test_dir, names, list(range(len(names))), "TESTING",
                model.n_inputs, model.n_outputs)
            if xs is None or xs.shape[0] == 0:
                return self._skip_promote(
                    job, f"no loadable test rows under {test_dir}")
            # regression kernels (linear output head -- native LNN)
            # cannot be judged by argmax accuracy: a constant output
            # would score 100% on 1-wide targets.  Auto-promote picks
            # the objective from the SERVED kernel's head
            from ..models.kernel import is_regression

            objective = ("mse" if is_regression(model.kind)
                         else "accuracy")
            base_err, base_served, base_req = self._eval_generation(
                job.kernel, xs, ts, baseline, objective=objective)
            if base_served != {baseline}:
                # the baseline was pruned between the table read and
                # the eval (weights_for fell back): a decision against
                # the wrong weights would be worse than no decision
                return self._skip_promote(
                    job, f"baseline generation {baseline} no longer "
                    f"servable (got {sorted(base_served)})")
            cand_err, _cand_served, cand_req = self._eval_generation(
                job.kernel, xs, ts, candidate, objective=objective)
            canary = self.app.metrics.generation_requests(job.kernel)
            record = {
                "objective": objective,
                "test_dir": test_dir,
                "test_rows": int(xs.shape[0]),
                "candidate": candidate,
                "baseline": baseline,
                "candidate_err": round(cand_err, 6),
                "baseline_err": round(base_err, 6),
                "eval_requests": base_req + cand_req,
                # the existing A/B generation counters ARE the canary
                # evidence: how much traffic (canary fraction, pins,
                # and this eval) each generation actually served
                "canary_requests": {
                    str(candidate): canary.get(str(candidate), 0),
                    str(baseline): canary.get(str(baseline), 0)},
            }
            if cand_err <= base_err:
                model.promote()
                record["action"] = action = "auto_promoted"
            else:
                model.rollback(gen=baseline)
                # a rollback is a weights swap: lifecycle metrics stay
                # truthful, exactly like the operator endpoint
                self.app.metrics.count_reload(True)
                self.app.metrics.set_model_info(
                    model.name, model.generation, model.loaded_at)
                record["action"] = action = "auto_rolled_back"
            self.store.update(job, finalized=action,
                              auto_promote=record)
        nn_log.nn_event("auto_promote", job=job.job_id,
                        kernel=job.kernel, **record)
        nn_out(f"jobs: {job.job_id} {action}: candidate gen "
               f"{candidate} err {cand_err:.4f} vs baseline gen "
               f"{baseline} err {base_err:.4f} "
               f"({xs.shape[0]} test rows)\n")

    def _reload_into_serving(self, job: JobState, ckpt_dir: str,
                             watch_state: dict) -> None:
        result = self.app.poll_ckpt_reload(job.kernel, ckpt_dir,
                                           watch_state)
        if result is not None:
            self.store.update(job, generations=job.generations
                              + [int(result["generation"])])

    def _yield_to_eval(self, stop: threading.Event) -> None:
        """The preemption gate: while eval traffic is queued, the next
        epoch waits (bounded) -- serving latency beats training
        throughput on a shared device.  The wait is a span
        (``jobs.yield_to_eval``): generation-swap / device contention
        shows up in the job's trace as time spent here.

        Training resumes only after the queues stay drained for a
        short quiesce window: under a saturated closed-loop client the
        depth dips to zero for single ticks between a drain and the
        next arrivals, and a momentary zero must not let an epoch
        barge into a stream that is still hammering.  (With K slice
        workers this is also what lets concurrent jobs overlap their
        waits -- every worker defers through the same busy window
        instead of taking turns barging.)"""
        with obs_trace.span("jobs.yield_to_eval"):
            deadline = time.monotonic() + self.preempt_wait_s
            quiet = 0
            while not stop.is_set() and time.monotonic() < deadline:
                depths = [b.depth() for b in self.app.batchers.values()]
                if any(depths):
                    quiet = 0
                elif (quiet := quiet + 1) >= YIELD_QUIESCE_TICKS:
                    return
                time.sleep(0.001)

    def _write_console(self, job: JobState, entries: list) -> None:
        try:
            with open(os.path.join(job.path, JOB_CONSOLE), "w") as fp:
                for level, text in entries:
                    fp.write(_LOG_PREFIX.get(level, "") + text)
        except OSError:
            pass  # the log is a convenience, never a failure

    # --- control ----------------------------------------------------------
    def get(self, job_id: str) -> dict | None:
        return self.store.snapshot(job_id)

    def list(self) -> list[dict]:
        return self.store.list()

    def active(self) -> dict:
        """The running jobs (first id + its trace id, back-compat for
        the mesh worker heartbeat that advertises where a job runs and
        which ``?trace=job:<id>`` to pull fleet-wide -- ISSUE 10) and
        the queued count; ``running_jobs`` lists the whole pool."""
        with self._mu:
            ids = sorted(self._running)
        cur = ids[0] if ids else None
        return {"running": cur,
                "trace": f"job:{cur}" if cur else None,
                "running_jobs": ids,
                "queued": self.queue.depth()}

    def running_count(self) -> int:
        with self._mu:
            return len(self._running)

    def cancel(self, job_id: str) -> dict:
        """Cancel a queued job immediately, or latch the running job's
        stop event (the in-flight epoch finishes, a final snapshot is
        written, the job lands ``cancelled`` -- resumable)."""
        job = self.store.get(job_id)
        if job is None:
            raise KeyError(job_id)
        if self.queue.remove(job_id):
            self.store.update(job, status="cancelled",
                              error="cancelled while queued",
                              finished=time.time())
            return self.store.snapshot(job_id)
        with self._mu:
            run = self._running.get(job_id)
            if run is not None:
                run["cancel"] = True
                run["stop"].set()
                return self.store.snapshot(job_id)
            if job.status not in TERMINAL_STATES:
                # TOCTOU window: a worker popped the job from the
                # queue but has not installed it as running yet (or
                # pause() is cycling it through requeue_front).  Latch
                # the cancel; the worker honors it at install time.
                self._pending_cancel.add(job_id)
                return self.store.snapshot(job_id)
        raise JobError(f"job '{job_id}' already {job.status}")

    def finalize(self, job_id: str, how: str) -> None:
        job = self.store.get(job_id)
        if job is not None:
            self.store.update(job, finalized=how)

    def pause(self) -> None:
        """Hold the worker between jobs (queue keeps admitting) -- test
        / operations hook, same spirit as MicroBatcher.pause."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def drain(self, timeout_s: float = 120.0) -> None:
        """Graceful shutdown: stop admitting, latch every running job's
        stop event (finish the in-flight epoch + final snapshot, mark
        them ``interrupted``), park queued jobs as
        interrupted/resumable."""
        with self._mu:
            self._draining = True
            for run in self._running.values():
                run["stop"].set()
            open_uploads = list(self._uploads)
        for job_id in open_uploads:
            # open chunked uploads die with the server: chunk litter is
            # swept; the marker stays, so a recovered job re-queues and
            # fails its bounded upload wait instead of training partial
            self._drop_upload(job_id, aborted=True)
        self.queue.close()
        self._closed = True
        self.slices.close()
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if any(t.is_alive() for t in self._threads):  # pragma: no cover
            nn_warn("jobs: scheduler did not drain in time\n")
        # anything still queued never ran: park it resumable
        while True:
            job = self.queue.take(timeout_s=0.0)
            if job is None:
                break
            self.store.update(job, status="interrupted",
                              error="server shutdown before run",
                              finished=time.time())

    # --- observability ----------------------------------------------------
    def metrics_snapshot(self) -> dict:
        with self._mu:
            ids = sorted(self._running)
        running_jobs = []
        for job_id in ids:
            snap = self.store.snapshot(job_id) or {}
            errs = snap.get("errors") or []
            running_jobs.append({
                "job": job_id,
                "kernel": snap.get("kernel"),
                "epoch": snap.get("epoch", 0),
                "epochs": snap.get("epochs", 0),
                "mean_err": errs[-1] if errs else None,
                "slice": snap.get("slice"),
            })
        occ = self.slices.occupancy()
        return {
            "queue_depth": self.queue.depth(),
            # "running" keeps its single-job shape (first of the pool)
            # for the committed dashboards; "running_jobs" is the pool
            "running": running_jobs[0] if running_jobs else None,
            "running_jobs": running_jobs,
            "workers": self.workers,
            "slices_active": occ["slices_active"],
            "slice_devices_in_use": occ["devices_in_use"],
            "slice_devices_total": occ["devices_total"],
            "queued_placements": occ["queued_placements"],
            "by_status": self.store.by_status(),
            "trained_epochs_total": self.store.trained_epochs(),
            "auto_resumes_total": self.auto_resumes_total,
            "upload_chunks_total": self.upload_chunks_total,
        }
