"""Online training service: train-while-serving job subsystem.

The paper's premise -- "train the ANN while the host program runs" --
as a service embedded in the serve process: ``POST
/v1/kernels/<name>/train`` submits a training job into a bounded queue,
one scheduler worker time-slices the device against the micro-batching
eval queue at epoch granularity, every epoch-boundary snapshot
hot-reloads into the serving registry (with A/B generation pinning),
and job state persists through ``io/atomic.py`` so a restarted server
reports its full history.

* :mod:`state`     -- persistent :class:`JobState` records + the
  directory-backed :class:`JobStore` (crash recovery to ``interrupted``);
* :mod:`queue`     -- the bounded FIFO :class:`JobQueue`
  (:class:`JobQueueFull` -> HTTP 429);
* :mod:`scheduler` -- the :class:`JobScheduler` worker: reentrant
  ``api.train_job`` runs, epoch-boundary snapshot/reload/yield,
  cancel + graceful drain (ckpt signal machinery reused).
"""

from .queue import JobQueue, JobQueueFull
from .scheduler import JobScheduler
from .state import (
    ACTIVE_STATES,
    JOB_STATES,
    TERMINAL_STATES,
    JobError,
    JobState,
    JobStore,
)

__all__ = [
    "ACTIVE_STATES", "JOB_STATES", "TERMINAL_STATES",
    "JobError", "JobQueue", "JobQueueFull", "JobScheduler",
    "JobState", "JobStore",
]
